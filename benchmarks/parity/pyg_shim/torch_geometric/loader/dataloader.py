"""DataLoader: a torch.utils.data.DataLoader whose collate_fn is PyG's
Batch.from_data_list (the reference uses batch_size + shuffle only,
pert_gnn.py:201-209)."""

from __future__ import annotations

import torch

from torch_geometric.data.data import Batch


class DataLoader(torch.utils.data.DataLoader):
    def __init__(self, dataset, batch_size: int = 1, shuffle: bool = False,
                 **kwargs):
        super().__init__(dataset, batch_size=batch_size, shuffle=shuffle,
                         collate_fn=Batch.from_data_list, **kwargs)
