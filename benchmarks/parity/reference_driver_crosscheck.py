"""Execute the reference's TRAINING DRIVER (pert_gnn.py) verbatim.

Completes what reference_crosscheck.py starts (VERDICT r3 "missing" #1):
after the reference's own preprocess.py builds processed/ artifacts in a
sandbox, this harness runs /root/reference/pert_gnn.py — its lru_cache
get_x featurizer, mixture assembly, PyG collation, positional split,
quantile loss and metric denominators — on a minimal torch_geometric
SHIM (benchmarks/parity/pyg_shim; see its docstring for exactly what
the shim does and does not independently pin).

Checks:
1. The driver RUNS end-to-end (both graph types): per-epoch metric
   lines parse, losses finite, train MAE decreases.
2. EXACT train-time featurization parity: the x matrix the reference's
   get_x assembles for every unique (entry, ts_bucket) pair equals our
   `ResourceLookup` gather on the same mixture, row-matched through the
   per-runtime canonical (ms, occurrence) labels and the ms bijection
   (ref ms ints differ from ours — recovered as in
   reference_crosscheck.py). Pins pert_gnn.py:40-67 (incl. the
   1=missing indicator convention) against batching/featurize.py.
3. Magnitude sanity: the reference driver's final train MAE and our
   fit() under matched hparams (raw labels, lr 3e-4) land within 2x —
   different init/shuffle streams, so exactness is not expected here.

Run:  python benchmarks/parity/reference_driver_crosscheck.py
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile

import numpy as np
import pandas as pd

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(HERE))
REFERENCE = os.environ.get("PERTGNN_REFERENCE_DIR", "/root/reference")
SHIM = os.path.join(HERE, "pyg_shim")
sys.path.insert(0, REPO)

from benchmarks.parity.reference_crosscheck import (  # noqa: E402
    Check, canonical_nodes, make_sandbox, ms_bijection,
    read_like_reference, run_reference)


def run_reference_driver(root: str, graph_type: str,
                         epochs: int) -> subprocess.CompletedProcess:
    """pert_gnn.py verbatim under the shim. Wrapper compat (documented,
    logic untouched): pandas-3 legacy string dtype, and torch.load
    defaulting back to weights_only=False (torch >= 2.6 flipped the
    default; the reference predates it)."""
    wrapper = os.path.join(root, "_run_driver_shim.py")
    ref_path = os.path.join(REFERENCE, "pert_gnn.py")
    with open(wrapper, "w") as f:
        f.write(f"""\
import functools
import pandas as pd
import torch
pd.set_option('future.infer_string', False)
torch.load = functools.partial(torch.load, weights_only=False)
import runpy
runpy.run_path({ref_path!r}, run_name='__main__')
""")
    env = dict(os.environ, PYTHONPATH=f"{SHIM}:{REFERENCE}",
               PYTHONHASHSEED="0", JAX_PLATFORMS="")
    # 1200 s per driver run keeps the harness's worst case under the
    # in-suite wrapper's outer timeout (tests/test_reference_driver_
    # crosscheck.py), so cleanup always runs in THIS process's finally
    return subprocess.run(
        [sys.executable, wrapper, "--graph_type", graph_type,
         "--epochs", str(epochs), "--batch_size", "32"],
        cwd=root, env=env, capture_output=True, text=True, timeout=1200)


_EPOCH_RE = re.compile(
    r"Epoch: (\d+), Train: ([\d.eE+-]+|nan), Test mae: ([\d.eE+-]+|nan)")


def parse_epochs(stdout: str) -> list[dict]:
    rows = []
    for m in _EPOCH_RE.finditer(stdout):
        rows.append({"epoch": int(m.group(1)),
                     "train_mae": float(m.group(2)),
                     "test_mae": float(m.group(3))})
    return rows


def check_featurization(root: str, check: Check, graph_type: str) -> None:
    """EXACT: reference get_x output (saved in the driver's data list)
    == our ResourceLookup gather, row-matched per runtime block."""
    if SHIM not in sys.path:  # unpickling Data needs the shim importable
        sys.path.insert(0, SHIM)
    import torch

    from pertgnn_tpu.batching.featurize import ResourceLookup
    from pertgnn_tpu.batching.mixture import build_mixtures
    from pertgnn_tpu.config import Config
    from pertgnn_tpu.graphs.construct import build_runtime_graphs
    from pertgnn_tpu.ingest.assemble import assemble
    from pertgnn_tpu.ingest.preprocess import preprocess

    raw_df, raw_res = read_like_reference(root)
    cfg = Config()
    pre = preprocess(raw_df, raw_res, cfg.ingest)
    table = assemble(pre, cfg.ingest)
    graphs = build_runtime_graphs(pre, table, graph_type)
    mixtures = build_mixtures(graphs, table.entry2runtimes)
    lookup = ResourceLookup(pre.resources, missing_indicator_is_one=True)

    ref_df = pd.read_csv(os.path.join(root, "processed",
                                      "processed_df.csv"), engine="pyarrow")
    msmap = ms_bijection(check, pre.spans, ref_df)

    data_list = torch.load(
        os.path.join(root, "processed",
                     f"full_{graph_type}_data_list.pt"),
        weights_only=False)
    meta = table.meta  # same insertion order as tr2data (pinned already)
    check.ok("data_list_len", len(data_list) == len(meta),
             f"{len(data_list)} vs {len(meta)}")

    seen_pairs = set()
    feat_ok = True
    n_checked = 0
    for d, (_, row) in zip(data_list, meta.iterrows()):
        pair = (int(row["entry_id"]), int(row["ts_bucket"]))
        if pair in seen_pairs:
            continue
        seen_pairs.add(pair)
        mix = mixtures[pair[0]]
        # feature_mask: the reference's live pert get_x features only the
        # last stage-copy per ms (our default since this harness found it)
        my_x = lookup(np.full(mix.num_nodes, pair[1], dtype=np.int64),
                      mix.ms_id.astype(np.int64),
                      feature_mask=mix.feature_mask)
        ref_x = d.x.numpy()
        ref_ms = d.cat_X[:, 0].numpy()
        if ref_x.shape != my_x.shape:
            feat_ok = False
            continue
        # row match per runtime block via canonical (ms, occurrence);
        # blocks follow entry2runtimes order on both sides
        sizes = [graphs[rid].num_nodes
                 for rid in table.entry2runtimes[pair[0]][0]]
        off = 0
        for size in sizes:
            ref_rows = canonical_nodes(
                [msmap[int(m)] for m in ref_ms[off:off + size]])
            my_rows = canonical_nodes(mix.ms_id[off:off + size])
            index = {lab: i for i, lab in enumerate(my_rows)}
            perm = [index.get(lab, -1) for lab in ref_rows]
            if -1 in perm:
                feat_ok = False
                break
            if not np.array_equal(ref_x[off:off + size],
                                  my_x[off:off + size][perm]):
                feat_ok = False
                break
            off += size
        n_checked += 1
    check.ok(f"{graph_type}_get_x_exact", feat_ok,
             "reference get_x != ResourceLookup")
    check.ok(f"{graph_type}_pairs_checked", n_checked > 3, str(n_checked))


def my_fit_mae(root: str, graph_type: str, epochs: int) -> float:
    """Our fit() under the reference driver's hparams (raw labels)."""
    import dataclasses

    import jax
    jax.config.update("jax_platforms", "cpu")  # never dial the axon relay

    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import Config, DataConfig, TrainConfig
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.train.loop import fit

    raw_df, raw_res = read_like_reference(root)
    cfg = Config(graph_type=graph_type,
                 data=DataConfig(batch_size=32),
                 train=TrainConfig(lr=3e-4, label_scale=1.0, epochs=epochs,
                                   scan_chunk=4))
    pre = preprocess(raw_df, raw_res, cfg.ingest)
    ds = build_dataset(pre, cfg)
    _, hist = fit(ds, cfg)
    return float(hist[-1]["train_mae"])


def main():
    pd.set_option("future.infer_string", False)
    epochs = int(os.environ.get("DRIVER_EPOCHS", "3"))
    root = tempfile.mkdtemp(prefix="refdriver_")
    check = Check()
    fatal = None
    stats: dict = {}
    try:
        make_sandbox(root, traces_per_entry=110)
        pre = run_reference(root)
        if pre.returncode != 0:
            raise RuntimeError(
                f"reference preprocess failed: {pre.stderr[-1500:]}")
        for gtype in ("pert", "span"):
            proc = run_reference_driver(root, gtype, epochs)
            check.ok(f"{gtype}_driver_runs", proc.returncode == 0,
                     proc.stderr[-1500:])
            if proc.returncode != 0:
                continue
            rows = parse_epochs(proc.stdout)
            check.ok(f"{gtype}_epoch_lines", len(rows) == epochs,
                     f"{len(rows)} of {epochs}")
            finite = all(np.isfinite(r["train_mae"]) for r in rows)
            check.ok(f"{gtype}_losses_finite", finite)
            if rows:
                check.ok(f"{gtype}_train_decreases",
                         rows[-1]["train_mae"] < rows[0]["train_mae"],
                         f"{rows[0]['train_mae']} -> "
                         f"{rows[-1]['train_mae']}")
                stats[f"{gtype}_ref_train_mae"] = rows[-1]["train_mae"]
            check_featurization(root, check, gtype)
        # Magnitude sanity on pert (same corpus, matched hparams). The
        # reference's printed "Train" is total_loss/len — the PINBALL
        # loss, which at tau=0.5 is MAE/2 (the "train mae = qloss" quirk,
        # SURVEY.md §2.1); our train_mae is a true MAE, so the expected
        # ratio is ~2, not ~1. Observing it tightly around 2 is itself
        # evidence both stacks compute the same loss.
        if "pert_ref_train_mae" in stats:
            ours = my_fit_mae(root, "pert", epochs)
            stats["pert_our_train_mae"] = round(ours, 1)
            ratio = ours / max(2.0 * stats["pert_ref_train_mae"], 1e-9)
            stats["pert_mae_over_twice_ref_pinball"] = round(ratio, 3)
            check.ok("pert_magnitude_sane", 0.7 < ratio < 1.4,
                     f"ratio {ratio}")
    except Exception as e:  # noqa: BLE001 — verdict over traceback
        import traceback
        fatal = f"{type(e).__name__}: {e}"
        traceback.print_exc(file=sys.stderr)
    finally:
        ok = check.all_ok and fatal is None and bool(check.results)
        verdict = {"pass": ok, "checks": check.results,
                   "notes": check.notes, **stats}
        if fatal:
            verdict["fatal"] = fatal
        print(json.dumps(verdict, indent=1))
        import shutil
        shutil.rmtree(root, ignore_errors=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
