"""Pipelined-input-path benchmark: arena store + prefetch + overlap.

EXIT-CODE ASSERTS the four ISSUE-5 invariants (wall-clock numbers are
reported in the JSON; truth lives in the return code — same split as
coldstart_bench.py / chaos_bench.py):

(a) **warm arena store**: a SECOND real process pointed at a warm
    ``--arena_cache_dir`` does zero featurize/pack/ingest work
    (``arena.cache_hit`` >= 1, zero ``arena.build_seconds`` /
    ``arena.cache_miss`` / ``ingest.*`` events in its telemetry) and
    reaches BIT-IDENTICAL first-epoch train qloss;
(b) **prefetch ≡ eager**: the over-cap staging fallback with
    double-buffered prefetch (depth 2) produces bit-identical epoch
    qloss to the fully synchronous per-chunk path (depth 0) AND to the
    staged path;
(c) **overlapped serve dispatch**: at saturation on CPU, overlapped
    dispatch throughput >= `--overlap_tolerance` x synchronous
    dispatch, predictions bit-identical both ways, and the PR-4 chaos
    invariants (bisect quarantine, watchdog recovery, NaN guard) still
    pass under the SAME FaultPlans on the overlapped path;
(d) **starvation attribution**: ``prefetch.host_starved_s`` /
    ``prefetch.device_starved_s`` gauges land in the telemetry JSONL
    and are consistent with the iterator wall (the two sides are never
    blocked simultaneously, so their sum is bounded by the wall).

CPU by default (deterministic); faults are seeded and
occurrence-addressed.

    python benchmarks/pipeline_bench.py [--quick]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


class Check:
    def __init__(self):
        self.failures: list[str] = []

    def expect(self, cond: bool, what: str):
        if not cond:
            self.failures.append(what)
            print(f"PIPELINE FAIL: {what}", file=sys.stderr)


def _events(tele_dir: str) -> list[dict]:
    from pertgnn_tpu.telemetry import load_events
    out = []
    for fname in sorted(os.listdir(tele_dir)):
        if fname.endswith(".jsonl"):
            out.extend(load_events(os.path.join(tele_dir, fname)))
    return out


def _named(events: list[dict], name: str) -> list[dict]:
    return [e for e in events if e.get("name") == name]


# ---------------------------------------------------------------------------
# (a) warm-process arena store across real process boundaries
# ---------------------------------------------------------------------------

def scenario_arena_warm_process(check: Check, tmp: str) -> dict:
    arena = os.path.join(tmp, "arena")
    argv_base = [sys.executable, "-m", "pertgnn_tpu.cli.train_main",
                 "--synthetic", "--synthetic_entries", "3",
                 "--synthetic_traces_per_entry", "60",
                 "--min_traces_per_entry", "5", "--label_scale", "1000",
                 "--batch_size", "16", "--hidden_channels", "8",
                 "--graph_type", "pert", "--epochs", "1",
                 "--artifact_dir", os.path.join(tmp, "art"),
                 "--arena_cache_dir", arena]
    walls = {}
    for tag in ("cold", "warm"):
        tele = os.path.join(tmp, f"tele_{tag}")
        t0 = time.perf_counter()
        proc = subprocess.run(
            argv_base + ["--telemetry_dir", tele],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=1200)
        walls[tag] = time.perf_counter() - t0
        check.expect(proc.returncode == 0,
                     f"arena {tag} train process exited "
                     f"{proc.returncode}: {proc.stderr[-800:]}")
        if proc.returncode != 0:
            return {"failed": tag}
    cold = _events(os.path.join(tmp, "tele_cold"))
    warm = _events(os.path.join(tmp, "tele_warm"))
    check.expect(len(_named(cold, "arena.cache_miss")) >= 1
                 and len(_named(cold, "arena.build_seconds")) >= 1,
                 "arena: cold process did not record a miss + build")
    check.expect(len(_named(warm, "arena.cache_hit")) >= 1,
                 "arena: warm process recorded no cache hit")
    check.expect(len(_named(warm, "arena.cache_miss")) == 0
                 and len(_named(warm, "arena.build_seconds")) == 0,
                 "arena: warm process rebuilt (build counters nonzero)")
    # zero ingest/featurize/pack-build work in the warm process: the
    # ingest spans that the cold process necessarily emits are ABSENT
    ingest_cold = [e for e in cold
                   if str(e.get("name", "")).startswith("ingest.")]
    ingest_warm = [e for e in warm
                   if str(e.get("name", "")).startswith("ingest.")]
    check.expect(len(ingest_cold) >= 1,
                 "arena: cold process shows no ingest spans (telemetry "
                 "broken? the comparison below would be vacuous)")
    check.expect(len(ingest_warm) == 0,
                 f"arena: warm process still ran ingest "
                 f"({[e['name'] for e in ingest_warm][:4]})")
    q_cold = [e["value"] for e in _named(cold, "train.epoch_qloss")]
    q_warm = [e["value"] for e in _named(warm, "train.epoch_qloss")]
    check.expect(bool(q_cold) and q_cold == q_warm,
                 f"arena: first-epoch qloss not bit-identical "
                 f"(cold={q_cold} warm={q_warm})")
    mmap_bytes = [e["value"] for e in _named(warm, "arena.mmap_bytes")]
    check.expect(bool(mmap_bytes) and mmap_bytes[0] > 0,
                 "arena: warm process reported no mmap bytes")
    return {"cold_wall_s": round(walls["cold"], 2),
            "warm_wall_s": round(walls["warm"], 2),
            "qloss": q_cold[:1], "mmap_bytes": mmap_bytes[:1]}


# ---------------------------------------------------------------------------
# (b) + (d) prefetch ≡ eager, with starvation gauges in the JSONL
# ---------------------------------------------------------------------------

def _fit_workload():
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    ModelConfig, TrainConfig)
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess

    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=100_000, batch_size=16),
        model=ModelConfig(hidden_channels=8, num_layers=1),
        train=TrainConfig(label_scale=1000.0, scan_chunk=2),
        graph_type="pert",
    )
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=40, num_entries=6, patterns_per_entry=3,
        traces_per_entry=120, seed=11))
    pre = preprocess(data.spans, data.resources, cfg.ingest)
    return build_dataset(pre, cfg), cfg


def scenario_prefetch_numerics(check: Check, tele_dir: str) -> dict:
    from pertgnn_tpu.train.loop import fit

    ds, cfg = _fit_workload()

    def run(stage: bool | None, cap_mb: float, depth: int):
        c = cfg.replace(train=dataclasses.replace(
            cfg.train, stage_epoch_recipes=stage,
            stage_recipes_max_mb=cap_mb, prefetch_depth=depth))
        t0 = time.perf_counter()
        _, hist = fit(ds, c, epochs=1)
        return hist[0]["train_qloss"], time.perf_counter() - t0

    run(True, 256.0, 2)  # untimed warmup: the chunk-program compile
    # forced-staged with a tiny cap -> the over-cap fallback, i.e. the
    # per-chunk transfer regime the prefetch double-buffers
    q_prefetch, w_prefetch = run(True, 1e-6, 2)
    q_eager, w_eager = run(True, 1e-6, 0)
    q_staged, w_staged = run(True, 256.0, 2)
    check.expect(q_prefetch == q_eager,
                 f"prefetch: fallback qloss differs from eager "
                 f"({q_prefetch} vs {q_eager})")
    check.expect(q_prefetch == q_staged,
                 f"prefetch: fallback qloss differs from staged "
                 f"({q_prefetch} vs {q_staged})")
    return {"qloss": q_prefetch,
            "wall_prefetch_s": round(w_prefetch, 3),
            "wall_eager_s": round(w_eager, 3),
            "wall_staged_s": round(w_staged, 3)}


def scenario_starvation_gauges(check: Check, tele_dir: str) -> dict:
    from pertgnn_tpu import telemetry

    telemetry.get_bus().flush()
    events = _events(tele_dir)
    host = _named(events, "prefetch.host_starved_s")
    dev = _named(events, "prefetch.device_starved_s")
    wall = _named(events, "prefetch.wall_s")
    check.expect(bool(host) and bool(dev) and bool(wall),
                 "starvation: prefetch gauges missing from the JSONL")
    if not (host and dev and wall):
        return {}
    check.expect(len(_named(events, "train.staging_fallback")) >= 1,
                 "starvation: train.staging_fallback counter missing "
                 "(which transfer regime was measured?)")
    # per emission: the two sides are never blocked at the same instant,
    # so starved_host + starved_device <= iterator wall (+ scheduler
    # slack). The residual (wall - sum) is the overlapped useful work —
    # what the gauges exist to attribute.
    sums, walls = [], []
    for h, d, w in zip(host, dev, wall):
        s = h["value"] + d["value"]
        sums.append(s)
        walls.append(w["value"])
        check.expect(s <= w["value"] * 1.5 + 0.1,
                     f"starvation: starved sum {s:.3f}s exceeds "
                     f"iterator wall {w['value']:.3f}s")
    return {"n_windows": len(sums),
            "starved_sum_s": round(sum(sums), 4),
            "iter_wall_s": round(sum(walls), 4)}


# ---------------------------------------------------------------------------
# (c) overlapped serve dispatch: throughput + chaos invariants
# ---------------------------------------------------------------------------

def scenario_serve_overlap(check: Check, quick: bool,
                           tolerance: float) -> dict:
    import chaos_bench

    from pertgnn_tpu.serve.queue import MicrobatchQueue

    ds, cfg, state, engine = chaos_bench.build_workload()
    n = 96 if quick else 256
    entries, tsb = chaos_bench.request_stream(ds, n)
    ref = chaos_bench.reference_preds(engine, entries, tsb)

    def throughput(overlap: bool) -> tuple[float, np.ndarray, dict]:
        with MicrobatchQueue(engine, flush_deadline_ms=2,
                             dispatch_timeout_s=60.0,
                             overlap_dispatch=overlap) as q:
            t0 = time.perf_counter()
            preds, errors = chaos_bench.drive(q, entries, tsb,
                                              concurrency=16)
            wall = time.perf_counter() - t0
            stats = q.stats_dict()
        check.expect(not errors,
                     f"overlap={overlap}: {len(errors)} request errors")
        return len(entries) / wall, preds, stats

    # interleave repetitions so machine noise hits both modes alike
    reps = 2 if quick else 3
    rps_over, rps_sync = [], []
    for _ in range(reps):
        r_s, p_s, st_s = throughput(False)
        r_o, p_o, st_o = throughput(True)
        rps_sync.append(r_s)
        rps_over.append(r_o)
        check.expect((p_o == ref).all(),
                     "overlap: predictions not bit-identical to solo")
        check.expect((p_s == ref).all(),
                     "sync: predictions not bit-identical to solo")
    check.expect(st_o["overlapped"] >= 1,
                 "overlap: no batch was actually overlapped")
    check.expect(st_s["overlapped"] == 0,
                 "sync: control unexpectedly overlapped")
    best_over, best_sync = max(rps_over), max(rps_sync)
    check.expect(best_over >= tolerance * best_sync,
                 f"overlap throughput {best_over:.1f} rps < "
                 f"{tolerance:.2f} x sync {best_sync:.1f} rps")

    # PR-4 chaos invariants on the OVERLAPPED path, same FaultPlans:
    # chaos_bench's scenarios build queues with the config default
    # (overlap on) — rerunning them here pins the overlap + faults
    # composition in this bench's exit code too
    chaos = {}
    ch_entries, ch_tsb = chaos_bench.request_stream(ds, 48)
    ch_ref = chaos_bench.reference_preds(engine, ch_entries, ch_tsb)
    chaos["dispatch_error"] = chaos_bench.scenario_dispatch_error(
        ds, engine, ch_ref, ch_entries, ch_tsb, check)
    chaos["wedge"] = chaos_bench.scenario_wedge(
        ds, engine, ch_ref, ch_entries, ch_tsb, check)
    chaos["nan"] = chaos_bench.scenario_nan(
        ds, engine, ch_ref, ch_entries, ch_tsb, check)
    return {"rps_overlapped": [round(r, 1) for r in rps_over],
            "rps_sync": [round(r, 1) for r in rps_sync],
            "overlap_over_sync": round(best_over / best_sync, 3),
            "overlapped_batches": st_o["overlapped"],
            "chaos_under_overlap": chaos}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--quick", action="store_true",
                   help="smaller streams (CI-sized)")
    p.add_argument("--overlap_tolerance", type=float, default=0.9,
                   help="overlapped/sync throughput floor: CPU 'device' "
                        "compute shares cores with the host pack, so "
                        "the CPU assertion is 'no regression' (>= 0.9x) "
                        "rather than the accelerator win the overlap "
                        "targets")
    p.add_argument("--skip_arena", action="store_true",
                   help="skip the subprocess arena-store scenario")
    p.add_argument("--skip_drain", action="store_true",
                   help="skip the subprocess SIGTERM-drain scenario")
    args = p.parse_args(argv)

    from pertgnn_tpu import telemetry

    check = Check()
    t0 = time.perf_counter()
    tmp = tempfile.mkdtemp(prefix="pipeline_bench_")
    tele_dir = os.path.join(tmp, "tele_inproc")
    telemetry.configure(tele_dir, level="trace",
                        run_meta={"bench": "pipeline"})

    results = {}
    results["prefetch"] = scenario_prefetch_numerics(check, tele_dir)
    results["starvation"] = scenario_starvation_gauges(check, tele_dir)
    results["serve_overlap"] = scenario_serve_overlap(
        check, args.quick, args.overlap_tolerance)
    telemetry.shutdown()
    if not args.skip_arena:
        results["arena_warm_process"] = scenario_arena_warm_process(
            check, tmp)
    if not args.skip_drain:
        # graceful SIGTERM drain of a REAL serve_main child — which now
        # serves with overlapped dispatch by default, so this pins the
        # drain invariant (admissions stop, in-flight futures resolve,
        # exit 0) on the overlapped path
        import chaos_bench
        results["drain_under_overlap"] = chaos_bench.scenario_drain(
            check, args.quick)

    print(json.dumps({
        "metric": "pipeline_invariants_ok",
        "value": int(not check.failures),
        "unit": "bool",
        "scenarios": results,
        "violations": check.failures,
        "wall_s": round(time.perf_counter() - t0, 1),
        "tmp_dir": tmp,
        "captured_unix_time": time.time(),
    }))
    return 1 if check.failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
