"""Giant-corpus scale-out benchmark: sharded merge + SAR accumulation.

Exit-code-asserts the ISSUE-18 invariants in ONE run (wall-clock numbers
ride the JSON, the verdict lives in the return code — the
stream_bench/fleet_bench split):

- **sharded merge** — ``parallel/scale.sharded_merge`` over a 2-device
  CPU mesh must produce a Dataset BIT-IDENTICAL (every batch, every
  field, every split) to the single-host ``stream/merge.merge_shards``
  oracle, for EVERY tested delta permutation and host count 1..3, and
  the content-derived shard assignment must fingerprint-agree across
  simulated hosts.
- **SAR gradients** — the rematerialized accumulated gradient
  (``sar_grads_fn(remat=True)``) must equal the monolithic
  all-residuals-live twin BITWISE (tolerance 0, f32) at every tested
  bucket capacity, and the gradient must be nonzero (the assert is not
  vacuous).
- **zero fresh compiles** — one jitted SAR step serves EVERY live
  bucket count up to capacity: after stepping the full mixture, a
  2-bucket tail, and a 1-bucket tail, the jit cache holds exactly ONE
  executable.  Capacity is the only compiled dimension.
- **bounded memory** — the remat step's compiled temp-buffer bytes
  (XLA ``memory_analysis``: residual storage for the backward pass)
  must be STRICTLY below the monolithic twin's at >= 2 buckets — the
  headroom that lets the accumulated step scale the corpus without
  scaling peak HBM.

CPU by default (the mesh is 2 forced host-platform devices). One JSON
line on stdout.

    python benchmarks/scale_bench.py [--dryrun]

``--dryrun`` is the CI smoke (tiny corpus, 4 permutations, 2
capacities); the full run widens the corpus and sweeps every delta
permutation.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

import numpy as np  # noqa: E402


class Check:
    def __init__(self):
        self.failures: list[str] = []

    def expect(self, cond: bool, what: str):
        if not cond:
            self.failures.append(what)
            print(f"SCALE FAIL: {what}", file=sys.stderr)


def corpus_spec(dryrun: bool) -> dict:
    span = 9 * 60 * 1000
    return {"num_microservices": 14, "num_entries": 3,
            "patterns_per_entry": 3,
            "traces_per_entry": 30 if dryrun else 90,
            "seed": 11, "time_span_ms": span,
            "missing_resource_frac": 0.0,
            "ensure_pattern_coverage_before_ms": span // 4,
            "bounds": [span // 4, span // 2, 3 * span // 4]}


def make_corpus(spec: dict, cfg):
    """(base, deltas): the raw corpus sliced into base + 3 time-window
    delta shards, ingested in-process (the store is exercised by
    stream_bench; this bench isolates the merge/accumulate math)."""
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.assemble import assemble
    from pertgnn_tpu.ingest.preprocess import preprocess
    from pertgnn_tpu.stream import (base_shard, ingest_delta,
                                    shard_frames_by_window)

    gen_spec = {k: v for k, v in spec.items() if k != "bounds"}
    synth = synthetic.generate(synthetic.SyntheticSpec(**gen_spec))
    shards = shard_frames_by_window(synth.spans, synth.resources,
                                    spec["bounds"])
    pre0 = preprocess(shards[0][0], shards[0][1], cfg.ingest)
    table0 = assemble(pre0, cfg.ingest)
    base = base_shard(pre0, table0, cfg.graph_type, cfg.ingest)
    deltas = [ingest_delta(s, r, base, cfg.graph_type, cfg.ingest)
              for s, r in shards[1:]]
    return base, deltas


def make_cfg():
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    ModelConfig, TrainConfig)

    return Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=100_000, batch_size=4),
        model=ModelConfig(hidden_channels=16, num_layers=2),
        train=TrainConfig(label_scale=1000.0, scan_chunk=1,
                          device_materialize=False, epochs=2),
        graph_type="pert",
    )


def datasets_equal(a, b, tag: str, check: Check) -> bool:
    ok = True
    if set(a.splits) != set(b.splits):
        check.expect(False, f"{tag}: splits {set(a.splits)} != "
                            f"{set(b.splits)}")
        return False
    for name in a.splits:
        ba, bb = list(a.batches(name)), list(b.batches(name))
        if len(ba) != len(bb):
            check.expect(False, f"{tag}: {name} {len(ba)} vs {len(bb)} "
                                f"batches")
            ok = False
            continue
        for i, (x, y) in enumerate(zip(ba, bb)):
            for f in x._fields:
                if not np.array_equal(np.asarray(getattr(x, f)),
                                      np.asarray(getattr(y, f))):
                    check.expect(False, f"{tag}: {name} batch {i} "
                                        f"field {f} differs")
                    ok = False
                    break
    return ok


# -- phase: sharded merge vs oracle ---------------------------------------

def check_sharded_merge(check: Check, cfg, base, deltas,
                        dryrun: bool) -> dict:
    import jax

    from pertgnn_tpu.parallel import scale
    from pertgnn_tpu.parallel.mesh import make_mesh
    from pertgnn_tpu.stream import merge_shards

    t0 = time.perf_counter()
    oracle_ds, oracle_info = merge_shards(base, list(deltas), cfg)
    oracle_s = time.perf_counter() - t0

    mesh = make_mesh(data=2, model=1, devices=jax.devices()[:2])
    perms = list(itertools.permutations(range(len(deltas))))
    if dryrun:
        perms = perms[::max(1, len(perms) // 4)][:4]

    merge_s: dict[int, list[float]] = {}
    for hosts in (1, 2, 3):
        fp = scale.assignment_fingerprint(deltas, hosts)
        check.expect(
            all(scale.assignment_fingerprint(
                [deltas[i] for i in p], hosts) == fp for p in perms),
            f"assignment fingerprint order-dependent at hosts={hosts}")
        merge_s[hosts] = []
        for perm in perms:
            t0 = time.perf_counter()
            ds, info = scale.sharded_merge(
                base, [deltas[i] for i in perm], cfg, mesh,
                num_hosts=hosts)
            merge_s[hosts].append(time.perf_counter() - t0)
            datasets_equal(ds, oracle_ds,
                           f"merge hosts={hosts} perm={perm}", check)
            check.expect(
                info.shards == oracle_info.shards
                and info.new_entries == oracle_info.new_entries
                and info.new_topologies == oracle_info.new_topologies
                and info.dropped_coverage == oracle_info.dropped_coverage
                and (info.dropped_occurrence
                     == oracle_info.dropped_occurrence),
                f"MergeInfo drifts at hosts={hosts} perm={perm}")
    return {"oracle_merge_s": round(oracle_s, 4),
            "permutations": len(perms),
            "sharded_merge_s": {h: round(float(np.mean(v)), 4)
                                for h, v in merge_s.items()}}


# -- phase: SAR gradients + compiles + memory -----------------------------

def check_sar(check: Check, cfg, dataset, dryrun: bool) -> dict:
    import jax
    import jax.numpy as jnp

    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.parallel import scale
    from pertgnn_tpu.train.loop import create_train_state, make_tx

    model = make_model(cfg.model, dataset.num_ms, dataset.num_entries,
                       dataset.num_interfaces, dataset.num_rpctypes)
    tx = make_tx(cfg)
    batches = list(dataset.batches("train"))
    state = create_train_state(model, tx, batches[0], cfg.train.seed)
    n = len(batches)
    check.expect(n >= 2, f"corpus too small for >=2 buckets (n={n})")

    # gradient bit-equivalence, tolerance 0, at every tested capacity
    caps = [n, n + 2] if dryrun else [n, n + 1, n + 4]
    grad_equal = {}
    for cap in caps:
        buckets = jax.tree.map(jnp.asarray,
                               scale.bucket_batches(batches, cap))
        g_r = jax.jit(scale.sar_grads_fn(model, cfg, remat=True))(
            state.params, state.batch_stats, buckets)
        g_m = jax.jit(scale.sar_grads_fn(model, cfg, remat=False))(
            state.params, state.batch_stats, buckets)
        leaves_r = jax.tree.leaves(g_r)
        leaves_m = jax.tree.leaves(g_m)
        mismatched = [i for i, (a, b) in enumerate(zip(leaves_r, leaves_m))
                      if not np.array_equal(np.asarray(a), np.asarray(b))]
        check.expect(not mismatched,
                     f"cap={cap}: {len(mismatched)} gradient leaves "
                     f"differ remat vs monolithic")
        l1 = sum(float(np.abs(np.asarray(a)).sum()) for a in leaves_r)
        check.expect(l1 > 0, f"cap={cap}: gradient is identically zero "
                             f"(vacuous equality)")
        grad_equal[cap] = {"bitwise_equal": not mismatched,
                           "grad_l1": round(l1, 3)}

    # zero fresh compiles across live bucket counts at fixed capacity
    step = scale.make_sar_train_step(model, cfg, tx, remat=True)
    cap = n + 2
    st = jax.tree.map(jnp.array, state)  # the step donates its state
    for live in [n, min(2, n), 1]:
        buckets = jax.tree.map(jnp.asarray,
                               scale.bucket_batches(batches[:live], cap))
        st, metrics = step(st, buckets)
    compiles = step._cache_size()
    check.expect(compiles == 1,
                 f"live-count changes compiled fresh ({compiles} "
                 f"executables for one capacity)")
    check.expect(float(metrics["count"]) > 0,
                 "SAR step metrics empty at live=1")

    # remat temp bytes strictly below monolithic at >= 2 buckets
    abs_of = lambda t: jax.tree.map(  # noqa: E731
        lambda a: jax.ShapeDtypeStruct(np.asarray(a).shape,
                                       np.asarray(a).dtype), t)
    abs_s, abs_b = abs_of(state), abs_of(scale.bucket_batches(batches,
                                                              cap))
    remat_tmp = scale.step_temp_bytes(
        scale.make_sar_train_step(model, cfg, tx, remat=True),
        abs_s, abs_b)
    mono_tmp = scale.step_temp_bytes(
        scale.make_sar_train_step(model, cfg, tx, remat=False),
        abs_s, abs_b)
    if remat_tmp is None or mono_tmp is None:
        check.expect(False, "backend offers no memory_analysis — cannot "
                            "certify the remat memory bound")
    else:
        check.expect(remat_tmp < mono_tmp,
                     f"remat temp bytes not below monolithic "
                     f"({remat_tmp} >= {mono_tmp}) at {cap} buckets")
    return {"train_batches": n, "grad_equal": grad_equal,
            "sar_executables": compiles,
            "remat_temp_bytes": remat_tmp, "mono_temp_bytes": mono_tmp,
            "temp_headroom": (round(1 - remat_tmp / mono_tmp, 4)
                              if remat_tmp and mono_tmp else None)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dryrun", action="store_true",
                   help="CI smoke: tiny corpus, sampled permutations, "
                        "2 capacities")
    args = p.parse_args(argv)

    import jax

    check = Check()
    t0 = time.perf_counter()
    check.expect(len(jax.devices()) >= 2,
                 f"need a 2-device mesh, have {len(jax.devices())}")

    cfg = make_cfg()
    spec = corpus_spec(args.dryrun)
    base, deltas = make_corpus(spec, cfg)

    merge_report = check_sharded_merge(check, cfg, base, deltas,
                                       args.dryrun)

    from pertgnn_tpu.stream import merge_shards

    dataset, _info = merge_shards(base, list(deltas), cfg)
    sar_report = check_sar(check, cfg, dataset, args.dryrun)

    print(json.dumps({
        "bench": "scale", "dryrun": args.dryrun,
        "ok": not check.failures, "failures": check.failures,
        "wall_s": round(time.perf_counter() - t0, 2),
        "merge": merge_report, "sar": sar_report,
    }))
    return 1 if check.failures else 0


if __name__ == "__main__":
    sys.exit(main())
