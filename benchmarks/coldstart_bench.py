"""Cold-start benchmark: time-to-first-step and serve warmup, cold vs
warm compile cache, across REAL process boundaries.

Prints ONE JSON line:

  {"metric": "coldstart_ttfs_warm_speedup", "value": N, "unit": "x",
   "cold": {...}, "warm": {...}, "ttfs_speedup": N,
   "serve_warmup_speedup": N, "warm_serve_fresh_compiles": 0, ...}

What is measured (the ISSUE 3 acceptance evidence):

- Two CHILD PROCESSES run the identical workload against the same
  compile-cache dir. The first is the cold start (empty cache: every
  executable freshly XLA-compiled, then persisted); the second is the
  warm start (training chunk programs replay from JAX's persistent
  compilation cache; serve-rung executables deserialize from the AOT
  store). Process isolation is the point — in-process jit caches cannot
  fake a hit.
- **ttfs_s** — fit()'s time-to-first-train-step (model build + state
  init + first batch + first-chunk compile/replay + execution; the
  `ttfs_s` field of fit's first history row).
- **serve_warmup_s** — InferenceEngine.warmup() over the full bucket
  ladder, plus its compiles/deserialized counters and the XLA cache
  hit/miss counts observed by the whole child.

HARD-ASSERTED (exit 1): the warm child's serve warmup performs ZERO
fresh compiles (every rung deserialized) and its XLA cache records zero
misses for the train path. The ≥5x speedup claim is reported, not
asserted — wall-clock ratios belong in the JSON, invariants in the
exit code.

CPU by default (deterministic in this environment; pass-through via
PERTGNN_COLDSTART_PLATFORM for on-chip runs).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_workload(cache_dir: str, traces_per_entry: int = 300):
    """serve_bench's heterogeneous-shape synthetic corpus (>= 3 ladder
    rungs), with the compile cache wired into the Config."""
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import (CompileCacheConfig, Config, DataConfig,
                                    IngestConfig, ModelConfig, ServeConfig,
                                    TrainConfig)
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess

    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        # batch/chunk sized small: execution time rides BOTH sides of
        # the cold/warm ratio — the measurement targets compile cost
        data=DataConfig(max_traces=100_000, batch_size=32),
        model=ModelConfig(hidden_channels=32, num_layers=3),
        train=TrainConfig(label_scale=1000.0, scan_chunk=8),
        serve=ServeConfig(bucket_growth=2.0, max_graphs_per_batch=8,
                          min_bucket_nodes=128, min_bucket_edges=128),
        aot=CompileCacheConfig(cache_dir=cache_dir),
        graph_type="pert",
    )
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=60, num_entries=12, patterns_per_entry=3,
        pattern_size_range=(3, 24), traces_per_entry=traces_per_entry,
        seed=42))
    pre = preprocess(data.spans, data.resources, cfg.ingest)
    ds = build_dataset(pre, cfg)
    return ds, cfg


def child(cache_dir: str, traces_per_entry: int) -> dict:
    """One process's cold-start story: build data (excluded from the
    timings), fit one epoch (ttfs), warm the serve ladder."""
    from pertgnn_tpu.cli.common import apply_platform_env
    apply_platform_env()

    from pertgnn_tpu import telemetry
    from pertgnn_tpu.aot import enable_compile_cache
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.train.loop import fit

    t0 = time.perf_counter()
    ds, cfg = build_workload(cache_dir, traces_per_entry)
    data_s = time.perf_counter() - t0
    enable_compile_cache(cfg.aot)

    with telemetry.watch_xla_cache() as train_cache:
        state, hist = fit(ds, cfg, epochs=1)
    with telemetry.watch_xla_cache() as serve_cache:
        engine = InferenceEngine.from_dataset(ds, cfg, state).warmup()
    # one dispatch proves the deserialized executables actually serve
    s = ds.splits["test"]
    pred = engine.predict_many(s.entry_ids[:4], s.ts_buckets[:4])
    return {
        "data_s": round(data_s, 3),
        "ttfs_s": round(hist[0]["ttfs_s"], 3),
        "epoch0_s": round(hist[0]["train_time_s"], 3),
        "serve_warmup_s": round(engine.warmup_s, 3),
        "serve_buckets": len(engine.ladder),
        "serve_compiles": engine.compiles,
        "serve_deserialized": engine.deserialized,
        "train_xla_hits": train_cache["hits"],
        "train_xla_misses": train_cache["misses"],
        "serve_xla_hits": serve_cache["hits"],
        "serve_xla_misses": serve_cache["misses"],
        "first_predictions": [round(float(p), 4) for p in pred],
    }


def run_child(cache_dir: str, traces_per_entry: int) -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS",
                   os.environ.get("PERTGNN_COLDSTART_PLATFORM", "cpu"))
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--as-child",
         "--cache_dir", cache_dir,
         "--traces_per_entry", str(traces_per_entry)],
        capture_output=True, text=True, env=env)
    if out.returncode != 0:
        print(out.stdout, file=sys.stderr)
        print(out.stderr, file=sys.stderr)
        raise SystemExit(f"coldstart child failed rc={out.returncode}")
    # last stdout line is the child's JSON (logging chatter precedes it)
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--cache_dir", default="",
                   help="compile-cache dir (default: fresh temp dir, "
                        "removed afterwards)")
    p.add_argument("--traces_per_entry", type=int, default=300)
    p.add_argument("--as-child", action="store_true", dest="as_child",
                   help="internal: run one measurement process")
    args = p.parse_args()

    if args.as_child:
        print(json.dumps(child(args.cache_dir, args.traces_per_entry)))
        return 0

    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="coldstart_")
    cleanup = not args.cache_dir
    try:
        if os.path.isdir(cache_dir) and os.listdir(cache_dir):
            print(f"NOTE: cache dir {cache_dir} is not empty — the "
                  "'cold' phase may be partially warm", file=sys.stderr)
        cold = run_child(cache_dir, args.traces_per_entry)
        warm = run_child(cache_dir, args.traces_per_entry)

        ttfs_speedup = cold["ttfs_s"] / max(warm["ttfs_s"], 1e-9)
        warmup_speedup = (cold["serve_warmup_s"]
                          / max(warm["serve_warmup_s"], 1e-9))
        failures = []
        if warm["serve_compiles"] != 0:
            failures.append(
                f"warm serve warmup performed {warm['serve_compiles']} "
                "fresh compiles (want 0: every rung deserialized)")
        if warm["serve_deserialized"] != warm["serve_buckets"]:
            failures.append(
                f"warm serve deserialized {warm['serve_deserialized']}"
                f"/{warm['serve_buckets']} rungs")
        if warm["train_xla_misses"] != 0:
            failures.append(
                f"warm train path recorded {warm['train_xla_misses']} "
                "XLA cache misses (want 0: all programs replayed)")
        if warm["first_predictions"] != cold["first_predictions"]:
            failures.append(
                "deserialized executables predict differently than "
                "freshly compiled ones")
        result = {
            "metric": "coldstart_ttfs_warm_speedup",
            "value": round(ttfs_speedup, 2),
            "unit": "x",
            "ttfs_speedup": round(ttfs_speedup, 2),
            "serve_warmup_speedup": round(warmup_speedup, 2),
            "warm_serve_fresh_compiles": warm["serve_compiles"],
            "warm_train_xla_misses": warm["train_xla_misses"],
            "cold": cold,
            "warm": warm,
            "cache_dir": None if cleanup else cache_dir,
            "failures": failures,
            "captured_unix_time": time.time(),
        }
        print(json.dumps(result))
        return 1 if failures else 0
    finally:
        if cleanup:
            shutil.rmtree(cache_dir, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
