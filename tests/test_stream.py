"""Streaming subsystem: delta arenas, mixture merge, continual training,
blue/green rollout (pertgnn_tpu/stream/, fleet/rollout.py).

The load-bearing guarantees:

- appending delta shards IN ANY ORDER yields packed batches BIT-IDENTICAL
  to a from-scratch batch build over the concatenated raw shards
  (property-tested over shard permutations);
- new entries and new topologies merge vocab-stably; true vocabulary
  growth (new ms/interface/rpctype strings) is a LOUD VocabGrowth, and
  every situation the delta algebra cannot reproduce exactly is a loud
  StreamRebuildRequired, never an approximate merge;
- a corrupt delta-store entry re-ingests THAT SHARD only (warning +
  counter), the others stay warm, and the merged result is unchanged;
- continual fine-tuning warm-restarts from the latest checkpoint over
  the sliding window and refuses embeddings the corpus outgrew;
- the rollout controller swaps workers one at a time and rolls the
  failing slot back to the old checkpoint.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np
import pandas as pd
import pytest

from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                ModelConfig, StreamConfig, TrainConfig)
from pertgnn_tpu.ingest import synthetic
from pertgnn_tpu.ingest.assemble import assemble
from pertgnn_tpu.ingest.preprocess import preprocess
from pertgnn_tpu.ingest.schema import RESOURCE_COLUMNS, SPAN_COLUMNS
from pertgnn_tpu.stream import (DeltaArenaStore, StreamRebuildRequired,
                                VocabGrowth, base_shard, ingest_delta,
                                merge_shards, shard_frames_by_window)

SPAN_MS = 8 * 60 * 1000
BOUNDS = [SPAN_MS // 4, SPAN_MS // 2, 3 * SPAN_MS // 4]


def _cfg(**kw) -> Config:
    base = dict(ingest=IngestConfig(min_traces_per_entry=3),
                data=DataConfig(max_traces=10_000, batch_size=8),
                model=ModelConfig(hidden_channels=8),
                train=TrainConfig(label_scale=1000.0, epochs=1,
                                  device_materialize=False, scan_chunk=4),
                stream=StreamConfig(window_shards=2, finetune_epochs=1),
                graph_type="pert")
    base.update(kw)
    return Config(**base)


@pytest.fixture(scope="module")
def corpus():
    """(cfg, shards, base, deltas, oracle): one synthetic corpus sliced
    into base + 3 time-window shards, ingested once per module."""
    cfg = _cfg()
    synth = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=12, num_entries=3, patterns_per_entry=3,
        traces_per_entry=36, seed=5, time_span_ms=SPAN_MS,
        missing_resource_frac=0.0,
        ensure_pattern_coverage_before_ms=BOUNDS[0]))
    shards = shard_frames_by_window(synth.spans, synth.resources, BOUNDS)
    pre0 = preprocess(shards[0][0], shards[0][1], cfg.ingest)
    table0 = assemble(pre0, cfg.ingest)
    base = base_shard(pre0, table0, cfg.graph_type, cfg.ingest)
    deltas = [ingest_delta(s, r, base, cfg.graph_type, cfg.ingest)
              for s, r in shards[1:]]
    spans_u = pd.concat([s[0] for s in shards], ignore_index=True)
    res_u = pd.concat([s[1] for s in shards], ignore_index=True)
    oracle = build_dataset(preprocess(spans_u, res_u, cfg.ingest), cfg)
    return cfg, shards, base, deltas, oracle


def assert_same_dataset(a, b) -> None:
    assert a.budget == b.budget
    assert (a.num_ms, a.num_entries, a.num_interfaces, a.num_rpctypes) \
        == (b.num_ms, b.num_entries, b.num_interfaces, b.num_rpctypes)
    assert set(a.splits) == set(b.splits)
    for name in a.splits:
        sa, sb = a.splits[name], b.splits[name]
        for f in ("entry_ids", "ts_buckets", "ys"):
            np.testing.assert_array_equal(getattr(sa, f), getattr(sb, f))
        batches_a = list(a.batches(name))
        batches_b = list(b.batches(name))
        assert len(batches_a) == len(batches_b)
        for ba, bb in zip(batches_a, batches_b):
            for f in ba._fields:
                np.testing.assert_array_equal(getattr(ba, f),
                                              getattr(bb, f),
                                              err_msg=f"{name}:{f}")


# -- the bit-identical-merge contract --------------------------------------

def test_merge_matches_full_rebuild(corpus):
    cfg, _shards, base, deltas, oracle = corpus
    merged, info = merge_shards(base, deltas, cfg)
    assert_same_dataset(merged, oracle)
    assert len(info.shards) == 4
    assert info.dropped_coverage == 0 and info.dropped_occurrence == 0


def test_merge_reversed_order_identical(corpus):
    """Deterministic fallback for environments without hypothesis: the
    fully reversed shard order must also reproduce the oracle."""
    cfg, _shards, base, deltas, oracle = corpus
    merged, _info = merge_shards(base, deltas[::-1], cfg)
    assert_same_dataset(merged, oracle)


def test_merge_order_independence_property(corpus):
    """Appending shards in ANY order yields the SAME merged dataset —
    the property that makes the delta store append-only rather than
    sequence-sensitive."""
    pytest.importorskip("hypothesis")
    from hypothesis import given, settings
    from hypothesis import strategies as st

    cfg, _shards, base, deltas, oracle = corpus

    @settings(max_examples=6, deadline=None)
    @given(perm=st.permutations(list(range(len(deltas)))))
    def run(perm):
        merged, _info = merge_shards(base, [deltas[i] for i in perm], cfg)
        assert_same_dataset(merged, oracle)

    run()


# -- new entries / new topologies (the supported live cases) ---------------

def _handmade_delta_frames(base, n_traces: int, *, new_entry: bool,
                           t0: int):
    """Raw frames for a delta window after `t0`: traces of either a NEW
    entry (unused dm+interface string combination) or a NEW topology for
    an existing entry — all over EXISTING ms/interface/rpctype strings,
    so the shard ingests vocab-stably."""
    ms = [str(v) for v in np.asarray(base.vocabs["ms"])
          if str(v) != "(?)"]
    ifaces = [str(v) for v in np.asarray(base.vocabs["interface"])]
    existing_entries = set(base.entry_vocab)
    if new_entry:
        combo = None
        for dm in ms:
            for code, _s in enumerate(ifaces):
                if f"{dm}_{code}" not in existing_entries:
                    combo = (dm, ifaces[code])
                    break
            if combo:
                break
        assert combo is not None
        entry_dm, entry_iface = combo
    else:
        name = base.entry_vocab[0]
        entry_dm, code = name.rsplit("_", 1)
        entry_iface = ifaces[int(code)]
    rows = []
    buckets = set()
    for k in range(n_traces):
        tid = f"hand_{'e' if new_entry else 't'}_{k:04d}"
        start = t0 + 40_000 * k
        buckets.add(start // 30_000 * 30_000)
        rows.append((tid, start, "0", "(?)", "http", entry_dm,
                     entry_iface, 900.0 + k))
        # a 3-hop chain no synthetic pattern uses (novel topology)
        chain = [entry_dm, ms[1], ms[2], ms[3]]
        for h in range(3):
            rows.append((tid, start + 10 * (h + 1), f"0.{h + 1}",
                         chain[h], "rpc", chain[h + 1], ifaces[h],
                         100.0 - h))
    spans = pd.DataFrame(rows, columns=list(SPAN_COLUMNS))
    res_rows = [(b, m, 0.5, 0.5) for b in sorted(buckets)
                for m in (entry_dm, *ms[1:4])]
    resources = pd.DataFrame(res_rows, columns=list(RESOURCE_COLUMNS))
    return spans, resources


@pytest.mark.parametrize("new_entry", [False, True])
def test_new_topology_and_new_entry_merge(corpus, new_entry):
    cfg, shards, base, _deltas, _oracle = corpus
    t0 = SPAN_MS + 60_000
    spans_d, res_d = _handmade_delta_frames(base, 6, new_entry=new_entry,
                                            t0=t0)
    delta = ingest_delta(spans_d, res_d, base, cfg.graph_type, cfg.ingest)
    merged, info = merge_shards(base, [delta], cfg)
    spans_u = pd.concat([shards[0][0], spans_d], ignore_index=True)
    res_u = pd.concat([shards[0][1], res_d], ignore_index=True)
    oracle = build_dataset(preprocess(spans_u, res_u, cfg.ingest), cfg)
    assert_same_dataset(merged, oracle)
    assert info.new_topologies[1] >= 1
    assert info.new_entries[1] == (1 if new_entry else 0)
    if new_entry:
        assert merged.num_entries > len(base.entry_vocab)


# -- the loud refusals -----------------------------------------------------

def test_vocab_growth_is_loud(corpus):
    cfg, _shards, base, _deltas, _oracle = corpus
    spans_d, res_d = _handmade_delta_frames(base, 4, new_entry=False,
                                            t0=SPAN_MS + 60_000)
    spans_d.loc[1, "dm"] = "brand_new_microservice"
    with pytest.raises(VocabGrowth) as e:
        ingest_delta(spans_d, res_d, base, cfg.graph_type, cfg.ingest)
    assert "ms" in str(e.value) and "brand_new_microservice" in str(e.value)


def test_time_overlap_demands_rebuild(corpus):
    cfg, shards, base, deltas, _oracle = corpus
    back_in_time = shards[1][0].copy()
    back_res = shards[1][1].copy()
    back_in_time["traceid"] = "shift_" + back_in_time["traceid"]
    back_in_time["timestamp"] -= BOUNDS[0]  # interleaves the base window
    back_res["timestamp"] -= BOUNDS[0]
    delta = ingest_delta(back_in_time, back_res, base,
                         cfg.graph_type, cfg.ingest)
    with pytest.raises(StreamRebuildRequired) as e:
        merge_shards(base, [delta], cfg)
    assert e.value.reason == "shard_overlap"


def test_duplicate_traces_demand_rebuild(corpus):
    cfg, shards, base, deltas, _oracle = corpus
    fwd = shards[1][0].copy()
    fwd_res = shards[1][1].copy()
    fwd["timestamp"] += SPAN_MS  # ordering passes; trace ids collide
    fwd_res["timestamp"] += SPAN_MS
    dup = ingest_delta(fwd, fwd_res, base, cfg.graph_type, cfg.ingest)
    with pytest.raises(StreamRebuildRequired) as e:
        merge_shards(base, [deltas[0], dup], cfg)
    assert e.value.reason == "trace_overlap"


def test_resource_overlap_demands_rebuild(corpus):
    cfg, shards, base, _deltas, _oracle = corpus
    spans_d, res_d = _handmade_delta_frames(base, 4, new_entry=False,
                                            t0=SPAN_MS + 60_000)
    # repeat one of the BASE's (ts_bucket, ms) resource groups
    clash = shards[0][1].iloc[:1]
    res_d = pd.concat([res_d, clash], ignore_index=True)
    delta = ingest_delta(spans_d, res_d, base, cfg.graph_type, cfg.ingest)
    with pytest.raises(StreamRebuildRequired) as e:
        merge_shards(base, [delta], cfg)
    assert e.value.reason == "resource_overlap"


def test_filter_drift_demands_rebuild():
    """An entry the base occurrence filter DROPPED that delta growth
    pushes back over the threshold: the batch rebuild would resurrect
    base traces the stream no longer has — loud rebuild, not an
    approximate merge."""
    cfg = _cfg()
    thr = cfg.ingest.min_traces_per_entry

    def frames(prefix, n_traces, t0, entry_iface="if_a"):
        rows = []
        buckets = set()
        for k in range(n_traces):
            tid = f"{prefix}_{k:04d}"
            start = t0 + 40_000 * k
            buckets.add(start // 30_000 * 30_000)
            rows.append((tid, start, "0", "(?)", "http", "svc_a",
                         entry_iface, 500.0 + k))
            rows.append((tid, start + 5, "0.1", "svc_a", "rpc", "svc_b",
                         "if_b", 50.0))
        spans = pd.DataFrame(rows, columns=list(SPAN_COLUMNS))
        res = pd.DataFrame([(b, m, 0.4, 0.4) for b in sorted(buckets)
                            for m in ("svc_a", "svc_b")],
                           columns=list(RESOURCE_COLUMNS))
        return spans, res

    # base: entry "if_a" well over the threshold, entry "if_rare"
    # UNDER it (dropped by the base build, recorded in the prefilter
    # occurrence stats)
    s1, r1 = frames("common", thr + 3, 0, entry_iface="if_a")
    s2, r2 = frames("rare", 2, 2_000_000, entry_iface="if_rare")
    pre = preprocess(pd.concat([s1, s2], ignore_index=True),
                     pd.concat([r1, r2], ignore_index=True), cfg.ingest)
    table = assemble(pre, cfg.ingest)
    base = base_shard(pre, table, cfg.graph_type, cfg.ingest)
    # delta: 2 more traces of the rare entry -> 2 + 2 > 3 would pass
    s3, r3 = frames("rare2", 2, 4_000_000, entry_iface="if_rare")
    delta = ingest_delta(s3, r3, base, cfg.graph_type, cfg.ingest)
    with pytest.raises(StreamRebuildRequired) as e:
        merge_shards(base, [delta], cfg)
    assert e.value.reason == "filter_drift"

    # a LEGACY base (no prefilter stats) must fail CLOSED: the counts
    # are unknown, so any delta of an entry the base knew-but-dropped
    # is refused even when the delta alone stays under the threshold
    legacy = dataclasses.replace(base, entry_occ_prefilter=None)
    s4, r4 = frames("rare3", 1, 6_000_000, entry_iface="if_rare")
    delta1 = ingest_delta(s4, r4, legacy, cfg.graph_type, cfg.ingest)
    with pytest.raises(StreamRebuildRequired) as e:
        merge_shards(legacy, [delta1], cfg)
    assert e.value.reason == "filter_drift"


def test_coverage_drift_demands_rebuild():
    """A delta carrying the FIRST resource rows for an ms the base
    never resourced, while the base's coverage filter dropped traces:
    the batch rebuild could resurrect them — loud rebuild."""
    cfg = _cfg()

    def trace(rows, tid, t0, children):
        rows.append((tid, t0, "0", "(?)", "http", "svc_a", "if_a",
                     500.0))
        for h, dm in enumerate(children):
            rows.append((tid, t0 + 5 * (h + 1), f"0.{h + 1}", "svc_a",
                         "rpc", dm, "if_b", 50.0))

    rows: list = []
    buckets = set()
    for k in range(6):  # survivors: {(?),a,d,e} -> 2/4 ... need >= 0.6
        t0 = 40_000 * k
        buckets.add(t0 // 30_000 * 30_000)
        trace(rows, f"ok_{k}", t0, ["svc_d", "svc_e"])
    for k in range(6):  # dropped by coverage: {(?),a,c} -> 1/3 < 0.6
        t0 = 1_000_000 + 40_000 * k
        buckets.add(t0 // 30_000 * 30_000)
        trace(rows, f"cov_{k}", t0, ["svc_c"])
    # keep svc_c in the VOCAB via a surviving trace that touches it:
    # {(?),a,c,d,e} -> 3/5 = 0.6 covered
    for k in range(6):
        t0 = 2_000_000 + 40_000 * k
        buckets.add(t0 // 30_000 * 30_000)
        trace(rows, f"mix_{k}", t0, ["svc_c", "svc_d", "svc_e"])
    spans = pd.DataFrame(rows, columns=list(SPAN_COLUMNS))
    res = pd.DataFrame([(b, m, 0.4, 0.4) for b in sorted(buckets)
                        for m in ("svc_a", "svc_d", "svc_e")],
                       columns=list(RESOURCE_COLUMNS))
    pre = preprocess(spans, res, cfg.ingest)
    table = assemble(pre, cfg.ingest)
    base = base_shard(pre, table, cfg.graph_type, cfg.ingest)
    assert base.coverage_dropped == 6
    assert "svc_c" in {str(v) for v in np.asarray(base.vocabs["ms"])}

    rows2: list = []
    b2 = set()
    for k in range(4):
        t0 = 4_000_000 + 40_000 * k
        b2.add(t0 // 30_000 * 30_000)
        trace(rows2, f"new_{k}", t0, ["svc_d"])
    spans2 = pd.DataFrame(rows2, columns=list(SPAN_COLUMNS))
    # the poison: first-ever resource rows for svc_c
    res2 = pd.DataFrame([(b, m, 0.4, 0.4) for b in sorted(b2)
                         for m in ("svc_a", "svc_d", "svc_c")],
                        columns=list(RESOURCE_COLUMNS))
    delta = ingest_delta(spans2, res2, base, cfg.graph_type, cfg.ingest)
    with pytest.raises(StreamRebuildRequired) as e:
        merge_shards(base, [delta], cfg)
    assert e.value.reason == "filter_drift"
    # without the poison row the same delta merges fine
    res2_ok = res2[res2["msname"] != "svc_c"]
    delta_ok = ingest_delta(spans2, res2_ok, base, cfg.graph_type,
                            cfg.ingest)
    merged, _ = merge_shards(base, [delta_ok], cfg)
    assert merged.num_entries >= 1


# -- the delta store -------------------------------------------------------

def test_store_roundtrip_and_corrupt_fallback(corpus, tmp_path, caplog):
    cfg, shards, base, deltas, oracle = corpus
    cfg = dataclasses.replace(cfg, stream=dataclasses.replace(
        cfg.stream, delta_store_dir=str(tmp_path / "delta")))
    store = DeltaArenaStore(cfg.stream.delta_store_dir)
    calls = {"base": 0, "delta": 0}

    def pre_table():
        calls["base"] += 1
        pre = preprocess(shards[0][0], shards[0][1], cfg.ingest)
        return pre, assemble(pre, cfg.ingest)

    def frames(i):
        def get():
            calls["delta"] += 1
            return shards[i]
        return get

    fp = lambda i: {"kind": "test_stream", "window": i}  # noqa: E731
    b1 = store.load_or_ingest_base(cfg, fp(0), pre_table)
    d1 = [store.load_or_ingest_delta(cfg, fp(i), frames(i), b1)
          for i in (1, 2, 3)]
    assert calls == {"base": 1, "delta": 3}
    # second round: ALL warm, zero ingest work
    b2 = store.load_or_ingest_base(cfg, fp(0), pre_table)
    d2 = [store.load_or_ingest_delta(cfg, fp(i), frames(i), b2)
          for i in (1, 2, 3)]
    assert calls == {"base": 1, "delta": 3}
    merged, _ = merge_shards(b2, d2, cfg)
    assert_same_dataset(merged, oracle)

    # corrupt ONE delta entry: only that shard re-ingests, loudly
    import glob
    victims = [p for p in glob.glob(str(tmp_path / "delta" / "*"))
               if os.path.isdir(p)]
    corrupted = 0
    for p in victims:
        import json as _json
        with open(os.path.join(p, "meta.json")) as f:
            if _json.load(f)["kind"] == "delta":
                with open(os.path.join(p, "traceid.npy"), "wb") as f:
                    f.write(b"garbage")
                corrupted = 1
                break
    assert corrupted
    with caplog.at_level("WARNING"):
        b3 = store.load_or_ingest_base(cfg, fp(0), pre_table)
        d3 = [store.load_or_ingest_delta(cfg, fp(i), frames(i), b3)
              for i in (1, 2, 3)]
    assert calls == {"base": 1, "delta": 4}  # exactly ONE re-ingest
    assert any("corrupt delta-store entry" in r.message
               for r in caplog.records)
    merged3, _ = merge_shards(b3, d3, cfg)
    assert_same_dataset(merged3, oracle)


# -- continual training ----------------------------------------------------

def test_window_split(corpus):
    cfg, _shards, base, deltas, _oracle = corpus
    merged, info = merge_shards(base, deltas, cfg)
    full = info.window_split(0)
    assert len(full) == len(info.meta)
    last2 = info.window_split(2)
    boundary = info.shards[-2][1]
    expected = info.meta[info.meta["traceid"] >= boundary]
    assert len(last2) == len(expected)
    assert 0 < len(last2) < len(full)


def test_check_capacity_refuses_growth(corpus):
    from pertgnn_tpu.stream import check_capacity

    cfg, _shards, _base, _deltas, oracle = corpus
    vocab = {"num_ms": oracle.num_ms, "num_entries": oracle.num_entries,
             "num_interfaces": oracle.num_interfaces,
             "num_rpctypes": oracle.num_rpctypes}
    check_capacity(oracle, cfg, vocab)  # no growth: fine
    with pytest.raises(StreamRebuildRequired) as e:
        check_capacity(oracle, cfg,
                       {**vocab, "num_entries": oracle.num_entries - 1})
    assert e.value.reason == "model_capacity"
    # headroom absorbs small growth inside one capacity window
    cfg_h = dataclasses.replace(cfg, model=dataclasses.replace(
        cfg.model, vocab_headroom_entries=64))
    check_capacity(oracle, cfg_h,
                   {**vocab, "num_entries": oracle.num_entries - 1})


def test_entry_capacity_rounding():
    from pertgnn_tpu.models.pert_model import entry_capacity

    assert entry_capacity(5, 0) == 5
    assert entry_capacity(5, 64) == 64
    assert entry_capacity(64, 64) == 64
    assert entry_capacity(65, 64) == 128


def test_finetune_round_warm_restarts(corpus, tmp_path):
    """One continual round: restores the latest checkpoint, trains the
    window for finetune_epochs, advances the checkpoint, and emits the
    drift gauge — and refuses to run without a checkpoint."""
    from pertgnn_tpu.stream import finetune_round
    from pertgnn_tpu.train.checkpoint import CheckpointManager
    from pertgnn_tpu.train.loop import fit

    cfg, _shards, base, deltas, _oracle = corpus
    cfg = dataclasses.replace(cfg, train=dataclasses.replace(
        cfg.train, checkpoint_dir=str(tmp_path / "ckpt")))
    merged, info = merge_shards(base, deltas, cfg)
    frozen = {"valid": merged.splits["valid"],
              "test": merged.splits["test"]}
    window = info.window_split(cfg.stream.window_shards)

    with pytest.raises(ValueError, match="warm-restart"):
        finetune_round(merged, window, frozen, cfg,
                       cfg.train.checkpoint_dir)

    ckpt = CheckpointManager(cfg.train.checkpoint_dir)
    _state, hist = fit(merged, cfg, epochs=1, checkpoint_manager=ckpt)
    ckpt.wait()

    class Cap:
        def __init__(self):
            self.gauges = {}

        def gauge(self, name, value, **tags):
            self.gauges[name] = value

        def counter(self, *a, **k):
            pass

        def histogram(self, *a, **k):
            pass

        def span(self, *a, **k):
            import contextlib
            return contextlib.nullcontext()

        enabled = True

        def flush(self):
            pass

    cap = Cap()
    _state2, hist2 = finetune_round(
        merged, window, frozen, cfg, cfg.train.checkpoint_dir, bus=cap,
        baseline_qloss=hist[-1]["valid_qloss"],
        checkpoint_vocab={"num_ms": merged.num_ms,
                          "num_entries": merged.num_entries,
                          "num_interfaces": merged.num_interfaces,
                          "num_rpctypes": merged.num_rpctypes})
    assert [h["epoch"] for h in hist2] == [1]  # warm restart, not epoch 0
    assert "stream.qloss_drift" in cap.gauges
    assert cap.gauges["stream.finetune_window"] == len(window)
    assert CheckpointManager(cfg.train.checkpoint_dir).latest_step() == 1


# -- the rollout controller ------------------------------------------------

class _FakeFleet:
    """Injectable process fabric for RolloutController: spawn/stop/probe
    are dict operations, readiness is scripted per worker."""

    def __init__(self, fail_new=(), fail_old=()):
        self.log: list[tuple[str, str]] = []
        self.version: dict[str, str] = {}
        self.fail_new = set(fail_new)
        self.fail_old = set(fail_old)

    def stop(self, w):
        self.log.append(("stop", w.worker_id))

    def spawn_new(self, w):
        self.log.append(("spawn_new", w.worker_id))
        self.version[w.worker_id] = ("broken" if w.worker_id
                                     in self.fail_new else "v2")
        return object()

    def spawn_old(self, w):
        self.log.append(("spawn_old", w.worker_id))
        self.version[w.worker_id] = ("broken" if w.worker_id
                                     in self.fail_old else "v1")
        return object()

    def probe(self, url, timeout_s):
        wid = url.rsplit("/", 1)[-1]
        v = self.version.get(wid)
        if v == "broken":
            return 503, {}
        return 200, {"version": v}


def _controller(fleet, workers, verify=None, **kw):
    from pertgnn_tpu.fleet.rollout import (RolloutController, RolloutWorker)

    ws = [RolloutWorker(worker_id=w, url=f"fake://{w}") for w in workers]
    return RolloutController(
        ws, stop_worker=fleet.stop, spawn_new=fleet.spawn_new,
        spawn_old=fleet.spawn_old, verify=verify, probe=fleet.probe,
        ready_timeout_s=1.0, poll_interval_s=0.01, **kw)


def test_rollout_swaps_worker_by_worker():
    fleet = _FakeFleet()
    out = _controller(
        fleet, ["w0", "w1"],
        verify=lambda body: None if body.get("version") == "v2"
        else f"version {body.get('version')}").run()
    assert out["swapped"] == ["w0", "w1"]
    assert fleet.log == [("stop", "w0"), ("spawn_new", "w0"),
                         ("stop", "w1"), ("spawn_new", "w1")]
    assert fleet.version == {"w0": "v2", "w1": "v2"}


def test_rollout_rolls_back_failed_readiness():
    from pertgnn_tpu.fleet.rollout import RolloutError

    fleet = _FakeFleet(fail_new={"w1"})
    with pytest.raises(RolloutError) as e:
        _controller(fleet, ["w0", "w1"]).run()
    assert e.value.rolled_back and e.value.worker_id == "w1"
    # the failed slot went back to v1; w0 stays on v2; the fleet whole
    assert fleet.version == {"w0": "v2", "w1": "v1"}
    assert ("spawn_old", "w1") in fleet.log


def test_rollback_not_judged_by_new_version_verify():
    """The rollback respawns the OLD checkpoint — the new-version
    verification must not apply to it, or every successful rollback
    would be misreported as a degraded fleet."""
    from pertgnn_tpu.fleet.rollout import RolloutError

    fleet = _FakeFleet(fail_new={"w1"})
    with pytest.raises(RolloutError) as e:
        _controller(fleet, ["w0", "w1"],
                    verify=lambda body: None if body.get("version") == "v2"
                    else f"version {body.get('version')}").run()
    assert e.value.rolled_back, str(e.value)  # v1 slot IS healthy
    assert fleet.version == {"w0": "v2", "w1": "v1"}


def test_rollout_reports_unrecovered_slot():
    from pertgnn_tpu.fleet.rollout import RolloutError

    fleet = _FakeFleet(fail_new={"w0"}, fail_old={"w0"})
    with pytest.raises(RolloutError) as e:
        _controller(fleet, ["w0", "w1"]).run()
    assert not e.value.rolled_back
    assert "degraded" in str(e.value)


def test_rollout_spawn_failure_rolls_back():
    """spawn_new RAISING (exec failure, bind race) must reach the same
    rollback path as failed readiness — never escape with the slot
    empty and no telemetry."""
    from pertgnn_tpu.fleet.rollout import RolloutError

    fleet = _FakeFleet()
    real_spawn = fleet.spawn_new

    def exploding(w):
        if w.worker_id == "w1":
            raise OSError("exec failed")
        return real_spawn(w)

    fleet.spawn_new = exploding
    with pytest.raises(RolloutError) as e:
        _controller(fleet, ["w0", "w1"]).run()
    assert e.value.rolled_back and e.value.worker_id == "w1"
    assert "spawn_new raised OSError" in str(e.value)
    assert fleet.version == {"w0": "v2", "w1": "v1"}


def test_rollout_verify_failure_counts_rollback():
    from pertgnn_tpu.fleet.rollout import RolloutError

    events = []

    class Bus:
        def counter(self, name, *a, **k):
            events.append(name)

        def histogram(self, *a, **k):
            pass

    fleet = _FakeFleet()
    with pytest.raises(RolloutError):
        _controller(fleet, ["w0"],
                    verify=lambda body: "always wrong",
                    bus=Bus()).run()
    assert "rollout.rollback" in events and "rollout.failed" in events
    assert "rollout.completed" not in events


# -- fingerprint modes + invalidation diagnostics --------------------------

def _fp_args(tmp_path, mode):
    import argparse

    return argparse.Namespace(artifact_dir="", synthetic=False,
                              data_dir=str(tmp_path),
                              stream_factorize=False,
                              fingerprint_mode=mode)


def test_content_fingerprint_survives_touch(tmp_path):
    from pertgnn_tpu.cli.common import raw_input_fingerprint

    f = tmp_path / "a.csv"
    f.write_text("x,y\n1,2\n")
    stat1 = raw_input_fingerprint(_fp_args(tmp_path, "stat"))
    cont1 = raw_input_fingerprint(_fp_args(tmp_path, "content"))
    os.utime(f, (1_000_000_000, 1_000_000_000))  # touch, same bytes
    stat2 = raw_input_fingerprint(_fp_args(tmp_path, "stat"))
    cont2 = raw_input_fingerprint(_fp_args(tmp_path, "content"))
    assert stat1 != stat2          # mtime churn invalidates stat keying
    assert cont1 == cont2          # ...but NOT content keying
    f.write_text("x,y\n1,3\n")     # a real edit invalidates both
    assert raw_input_fingerprint(_fp_args(tmp_path, "content")) != cont2
    assert cont1["files"][0][2].startswith("sha256:")


def test_invalidation_diff_names_exact_file():
    from pertgnn_tpu.batching.arena_store import ArenaStore

    prev = {"files": [["a.csv", 10, "sha256:aa"], ["b.csv", 5, "m1"]]}
    now = {"files": [["a.csv", 12, "sha256:bb"], ["c.csv", 7, "m2"]]}
    msgs = ArenaStore._diff_fingerprint_files(prev, now)
    joined = " | ".join(msgs)
    assert "a.csv" in joined and "changed" in joined
    assert "b.csv" in joined and "removed" in joined
    assert "c.csv" in joined and "added" in joined


# -- analyzer scope pins ---------------------------------------------------

def test_lock_discipline_scope_covers_stream():
    """The satellite pin: graftlint's lock-discipline pass must scan
    the streaming subsystem (and the fleet dir that holds rollout.py)
    from day one — a thread+lock added there later is checked the
    moment it appears."""
    from tools.graftlint.passes import lock_discipline

    assert "pertgnn_tpu/stream/" in lock_discipline.SCOPE
    assert any(s.startswith("pertgnn_tpu/fleet")
               for s in lock_discipline.SCOPE)
