"""ops/quantize: weight-only int8 quantization for the serve tier.

The REAL quality gate is benchmarks/serve_bench.py's quantile-loss
delta; these tests pin the mechanics — shape/dtype contracts, round-trip
error bounds, pytree structure, and the all-zero-channel edge case.
"""

import jax.numpy as jnp
import numpy as np

from pertgnn_tpu.ops.quantize import (dequantize_array, dequantize_tree,
                                      quantization_error, quantize_array,
                                      quantize_tree)


def test_roundtrip_error_bounded_by_one_step():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    q, scale = quantize_array(w)
    assert q.dtype == jnp.int8 and scale.shape == (1, 32)
    back = np.asarray(dequantize_array(q, scale, jnp.float32))
    # symmetric rounding: error <= scale/2 per element, per channel
    err = np.abs(back - np.asarray(w))
    assert (err <= np.asarray(scale)[0][None, :] * 0.5 + 1e-7).all()


def test_zero_channel_is_exact():
    w = jnp.zeros((8, 3), jnp.float32).at[:, 1].set(2.0)
    q, scale = quantize_array(w)
    back = np.asarray(dequantize_array(q, scale, jnp.float32))
    np.testing.assert_array_equal(back[:, 0], 0.0)
    np.testing.assert_allclose(back[:, 1], 2.0, rtol=1e-2)


def test_tree_structure_and_selective_quantization():
    """Only 2-D float leaves quantize; biases/stats/ints pass through,
    and dequantize_tree restores the original nesting."""
    params = {
        "dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.ones((4,))},
        "bn": {"scale": jnp.ones((4,)), "mean": jnp.zeros((4,))},
        "step": jnp.zeros((), jnp.int32),
    }
    q = quantize_tree(params)
    assert set(q["dense"]["kernel"]) == {"int8", "scale"}
    assert q["dense"]["bias"] is params["dense"]["bias"]
    assert q["step"] is params["step"]
    back = dequantize_tree(q, jnp.float32)
    assert back["dense"]["kernel"].shape == (4, 4)
    np.testing.assert_allclose(np.asarray(back["dense"]["kernel"]), 1.0,
                               rtol=1e-2)
    assert back["bn"]["mean"] is params["bn"]["mean"]


def test_quantization_error_probe():
    rng = np.random.default_rng(1)
    params = {"a": {"kernel": jnp.asarray(rng.normal(size=(16, 8)),
                                          jnp.float32)},
              "b": jnp.ones((8,))}
    report = quantization_error(params)
    assert report["quantized_leaves"] == 1
    # int8 symmetric: worst-case relative error ~ 1/(2*127)
    assert 0.0 < report["max_rel_error"] < 1.0 / 64
