"""Aux subsystems: artifact cache round-trip, orbax checkpoint/resume, CLIs."""

import json
import os
import sys

import numpy as np
import pandas as pd
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import Config, DataConfig, IngestConfig, ModelConfig, TrainConfig
from pertgnn_tpu.ingest.assemble import assemble
from pertgnn_tpu.ingest.io import (artifacts_present, load_artifacts,
                                   preprocess_cached, save_artifacts)


@pytest.fixture
def cfg():
    return Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=150, batch_size=8),
        model=ModelConfig(hidden_channels=8),
        train=TrainConfig(lr=1e-3, epochs=2, label_scale=1000.0),
    )


class TestArtifactCache:
    def test_round_trip(self, preprocessed, tmp_path, cfg):
        table = assemble(preprocessed)
        save_artifacts(str(tmp_path), preprocessed, table)
        assert artifacts_present(str(tmp_path))
        pre2, table2 = load_artifacts(str(tmp_path))
        pd.testing.assert_frame_equal(
            preprocessed.spans.reset_index(drop=True),
            pre2.spans.reset_index(drop=True))
        pd.testing.assert_frame_equal(table.meta, table2.meta)
        assert table2.runtime2trace == table.runtime2trace
        for k, (r, p) in table.entry2runtimes.items():
            r2, p2 = table2.entry2runtimes[k]
            np.testing.assert_array_equal(r, r2)
            np.testing.assert_allclose(p, p2)
        # and the loaded artifacts build an identical dataset
        ds1 = build_dataset(preprocessed, cfg, table)
        ds2 = build_dataset(pre2, cfg, table2)
        b1 = next(ds1.batches("train"))
        b2 = next(ds2.batches("train"))
        for f in b1._fields:
            np.testing.assert_array_equal(getattr(b1, f), getattr(b2, f), f)

    def test_cache_hit_skips_compute(self, synth, tmp_path, cfg):
        pre1, t1 = preprocess_cached(str(tmp_path), synth.spans,
                                     synth.resources, cfg=cfg.ingest)
        # poison the inputs: a cache hit must not recompute
        pre2, t2 = preprocess_cached(str(tmp_path), None, None,
                                     cfg=cfg.ingest)
        pd.testing.assert_frame_equal(t1.meta, t2.meta)


class TestCheckpoint:
    def test_save_restore_resume(self, preprocessed, tmp_path, cfg):
        from pertgnn_tpu.train.checkpoint import CheckpointManager
        from pertgnn_tpu.train.loop import fit

        ds = build_dataset(preprocessed, cfg)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
        state1, hist1 = fit(ds, cfg, epochs=2, checkpoint_manager=mgr)
        mgr.close()

        # resume: a fresh manager restores epoch 1 and runs only epoch 2
        mgr2 = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
        state2, hist2 = fit(ds, cfg, epochs=3, checkpoint_manager=mgr2)
        mgr2.close()
        assert [h["epoch"] for h in hist2] == [2]
        assert int(state2.step) > int(state1.step)

    def test_restore_preserves_params(self, preprocessed, tmp_path, cfg):
        import jax

        from pertgnn_tpu.train.checkpoint import CheckpointManager
        from pertgnn_tpu.train.loop import fit

        ds = build_dataset(preprocessed, cfg)
        mgr = CheckpointManager(str(tmp_path / "c2"), keep=1)
        state, _ = fit(ds, cfg, epochs=1, checkpoint_manager=mgr)
        mgr.wait()
        restored, start = mgr.maybe_restore(state)
        assert start == 1
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            jax.device_get(state.params), restored.params)
        mgr.close()


class TestShardedCheckpoint:
    def test_mesh_restore_preserves_shardings_and_values(self, preprocessed,
                                                         tmp_path, cfg):
        """Sharding-aware restore (VERDICT r2 #3): a TrainState trained on
        a mesh restores directly INTO its mesh shardings — no host-numpy
        round-trip — with identical values."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 fake devices")
        from pertgnn_tpu.parallel.mesh import make_mesh
        from pertgnn_tpu.train.checkpoint import CheckpointManager
        from pertgnn_tpu.train.loop import fit

        ds = build_dataset(preprocessed, cfg)
        mesh = make_mesh(data=4, model=2, devices=jax.devices()[:8])
        mgr = CheckpointManager(str(tmp_path / "ckm"), keep=1)
        state, _ = fit(ds, cfg, epochs=1, checkpoint_manager=mgr, mesh=mesh)
        mgr.wait()
        restored, start = mgr.maybe_restore(state)
        assert start == 1
        # restored leaves carry the live state's NamedShardings
        k_live = state.params["conv_0"]["query"]["kernel"]
        k_rest = restored.params["conv_0"]["query"]["kernel"]
        assert isinstance(k_rest.sharding, NamedSharding)
        assert k_rest.sharding == k_live.sharding
        # the kernel really is model-axis sharded (tensor-parallel rule),
        # so the equality above proved a NON-trivial sharded restore
        assert k_rest.sharding.spec == P(None, "model")
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            jax.device_get(state.params), jax.device_get(restored.params))
        mgr.close()


class TestBenchContract:
    def test_bench_emits_driver_json(self, tmp_path):
        """bench.py is the driver's interface: it must print ONE JSON line
        with the metric/value/unit/vs_baseline contract plus the round-3
        evidence fields (interleaved windows, spread, ceiling ratio)."""
        import subprocess

        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   BENCH_TRACES_PER_ENTRY="25", BENCH_WINDOWS="5")
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench.py")],
            env=env, cwd=_REPO, capture_output=True, text=True, timeout=900)
        assert out.returncode == 0, out.stderr[-2000:]
        line = out.stdout.strip().splitlines()[-1]
        row = json.loads(line)
        for key in ("metric", "value", "unit", "vs_baseline", "fit_windows",
                    "fit_spread_pct", "ceiling_graphs_per_s",
                    "fit_over_ceiling", "compact_ceiling_graphs_per_s",
                    "fit_over_compact_ceiling", "compact_over_packed",
                    "flops_per_graph", "backend",
                    # round-4 fields: MBU/roofline accounting (null on CPU)
                    "mbu_pct", "roofline_graphs_per_s", "bytes_per_graph",
                    "peak_hbm_bytes_per_s"):
            assert key in row, key
        assert row["unit"] == "graphs/s"
        assert row["value"] > 0
        assert len(row["fit_windows"]) == 5
        assert len(row["ceiling_windows"]) == 5
        assert row["backend"] == "cpu"


class TestProfiling:
    def test_profile_epochs_writes_trace(self, preprocessed, tmp_path, cfg):
        """fit(profile_hook=profile_epochs(...)) captures a jax.profiler
        trace for the chosen epoch (SURVEY.md §5.1 rebuild)."""
        from pertgnn_tpu.utils.profiling import StepTimer, profile_epochs

        ds = build_dataset(preprocessed, cfg)
        from pertgnn_tpu.train.loop import fit

        d = str(tmp_path / "prof")
        _, history = fit(ds, cfg, epochs=2,
                         profile_hook=profile_epochs(d, epochs=(0,)))
        assert len(history) == 2
        import glob
        assert glob.glob(os.path.join(d, "**", "*.pb"), recursive=True) or \
            glob.glob(os.path.join(d, "**", "*.json.gz"), recursive=True), \
            f"no trace artifacts under {d}"

        t = StepTimer()
        for _ in range(3):
            with t:
                pass
        assert "3 steps" in t.summary()


class TestFlops:
    def test_compiled_flops_counts_matmul(self):
        """XLA cost analysis of a bare matmul ~= 2*m*n*k FLOPs (the MFU
        denominator's numerator — utils/flops.py)."""
        import jax
        import jax.numpy as jnp

        from pertgnn_tpu.utils.flops import compiled_flops, mfu

        m = n = k = 128
        f = jax.jit(lambda a, b: a @ b)
        a = jnp.ones((m, k), jnp.float32)
        b = jnp.ones((k, n), jnp.float32)
        fl = compiled_flops(f, a, b)
        assert fl is not None
        assert 0.5 * 2 * m * n * k <= fl <= 2 * 2 * m * n * k
        # CPU has no peak table -> MFU None, never a bogus number
        assert mfu(1e6, fl) is None

    def test_peak_table_kinds(self):
        from pertgnn_tpu.utils.flops import (_PEAK_FLOPS_BY_KIND,
                                             _PEAK_HBM_BW_BY_KIND)

        for table in (_PEAK_FLOPS_BY_KIND, _PEAK_HBM_BW_BY_KIND):
            kinds = [k for k, _ in table]
            # longest-match-first ordering: "v5 lite"/"v5e" must precede "v5"
            assert kinds.index("v5e") < kinds.index("v5")
            assert kinds.index("v5 lite") < kinds.index("v5")
            assert kinds.index("v4 lite") < kinds.index("v4")

    def test_bytes_mbu_roofline(self, monkeypatch):
        """compiled_cost reports bytes; MBU and the roofline ceiling follow
        min(compute, bandwidth) against the (patched) chip peaks."""
        import jax
        import jax.numpy as jnp

        from pertgnn_tpu.utils import flops as F

        m = n = k = 128
        f = jax.jit(lambda a, b: a @ b)
        a = jnp.ones((m, k), jnp.float32)
        b = jnp.ones((k, n), jnp.float32)
        fl, by = F.compiled_cost(f, a, b)
        assert fl is not None and by is not None
        # 3 buffers of 128x128 f32 minimum
        assert by >= 3 * m * n * 4 * 0.5
        # CPU: no peaks -> all None, never bogus numbers
        assert F.mbu(1e6, by) is None
        assert F.roofline_graphs_per_s(fl, by) is None
        # patched peaks: intensity fl/by vs knee decides the binding roof
        monkeypatch.setattr(F, "peak_flops_per_chip", lambda: 100.0 * fl)
        monkeypatch.setattr(F, "peak_hbm_bw_per_chip", lambda: 10.0 * by)
        assert F.roofline_graphs_per_s(fl, by) == 10.0  # bandwidth-bound
        assert abs(F.mbu(10.0, by) - 1.0) < 1e-9        # at the roof
        assert abs(F.mfu(10.0, fl) - 0.1) < 1e-9


class TestCLI:
    def test_preprocess_then_train(self, tmp_path, capsys):
        from pertgnn_tpu.cli import preprocess_main, train_main

        art = str(tmp_path / "processed")
        preprocess_main.main([
            "--synthetic", "--min_traces_per_entry", "10",
            "--synthetic_entries", "3", "--synthetic_traces_per_entry", "30",
            "--artifact_dir", art])
        out = capsys.readouterr().out
        assert "runtime patterns" in out
        # second run: cache hit
        preprocess_main.main(["--artifact_dir", art])
        assert "nothing to do" in capsys.readouterr().out

        train_main.main([
            "--synthetic", "--min_traces_per_entry", "10",
            "--artifact_dir", art, "--epochs", "2", "--batch_size", "8",
            "--hidden_channels", "8", "--label_scale", "1000",
            "--graph_type", "pert"])
        out = capsys.readouterr().out
        assert "Epoch: 1" in out
        assert "graphs/s" in out

    def test_all_config_fields_settable(self):
        """Every Config field the benchmarks touch maps from a CLI flag
        (VERDICT r2 #10)."""
        import argparse

        from pertgnn_tpu.cli.common import (add_ingest_flags,
                                            add_model_train_flags,
                                            config_from_args)

        p = argparse.ArgumentParser()
        add_ingest_flags(p)
        add_model_train_flags(p)
        args = p.parse_args([
            "--attn_dropout", "0.1", "--use_pallas_attention",
            "--missing_indicator_is_zero", "--max_nodes_per_batch", "512",
            "--max_edges_per_batch", "1024", "--no_device_materialize",
            "--arena_hbm_budget_gb", "0", "--shard_edges",
            "--num_heads", "4", "--scan_chunk", "2",
            "--budget_headroom", "1.3"])
        c = config_from_args(args)
        assert c.model.attn_dropout == 0.1
        assert c.model.use_pallas_attention
        assert c.model.missing_indicator_is_one is False
        assert c.data.max_nodes_per_batch == 512
        assert c.data.max_edges_per_batch == 1024
        assert c.train.device_materialize is False
        assert c.train.arena_hbm_budget_gb is None
        assert c.parallel.shard_edges
        assert c.model.num_heads == 4
        assert c.train.scan_chunk == 2
        assert c.data.budget_headroom == 1.3

    def test_pipeline_flags_map_to_config(self):
        """ISSUE 5 knobs: staging tri-state, prefetch depth, arena
        cache dir, serve overlap — flags -> Config, including the
        legacy --no_stage_epoch_recipes alias."""
        import argparse

        from pertgnn_tpu.cli.common import (add_ingest_flags,
                                            add_model_train_flags,
                                            add_serve_flags,
                                            config_from_args)

        def parse(argv):
            p = argparse.ArgumentParser()
            add_ingest_flags(p)
            add_model_train_flags(p)
            add_serve_flags(p)
            return config_from_args(p.parse_args(argv))

        c = parse([])
        assert c.train.stage_epoch_recipes is None  # auto
        assert c.train.prefetch_depth == 2
        assert c.serve.overlap_dispatch is True
        assert c.data.arena_cache_dir == ""
        c = parse(["--staged_epochs", "on", "--prefetch_depth", "4",
                   "--arena_cache_dir", "/tmp/ac",
                   "--no_overlap_dispatch"])
        assert c.train.stage_epoch_recipes is True
        assert c.train.prefetch_depth == 4
        assert c.data.arena_cache_dir == "/tmp/ac"
        assert c.serve.overlap_dispatch is False
        assert parse(["--staged_epochs", "off"]
                     ).train.stage_epoch_recipes is False
        # legacy alias forces off even at the auto default
        assert parse(["--no_stage_epoch_recipes"]
                     ).train.stage_epoch_recipes is False

    def test_probe_verdict_cache_reused(self, tmp_path, monkeypatch,
                                        capsys):
        """A fresh cached verdict short-circuits the (minutes-long)
        backend probe; a cached fallback also re-applies the CPU
        platform env. BENCH_r05 burned 4x75 s per fallback run on
        identical dead-relay probes."""
        import json
        import time as _time

        from pertgnn_tpu.cli.common import probe_backend_or_fallback

        cache = tmp_path / "probe.json"
        cache.write_text(json.dumps(
            {"fallback": True, "probed_unix_time": _time.time()}))
        calls: list = []

        def fake_run(*a, **k):
            calls.append(1)
            raise RuntimeError("probe subprocess failed")

        monkeypatch.setattr("subprocess.run", fake_run)
        monkeypatch.setenv("BENCH_PROBE_TRIES", "1")
        monkeypatch.setenv("BENCH_PROBE_PAUSE", "0")
        # JAX_PLATFORMS="" = probe-eligible; the cached verdict must
        # answer WITHOUT spawning a probe subprocess
        monkeypatch.setenv("JAX_PLATFORMS", "")
        assert probe_backend_or_fallback(cache_path=str(cache)) is True
        assert not calls  # fresh verdict: no probe ran
        assert os.environ["JAX_PLATFORMS"] == "cpu"
        assert "reused" in capsys.readouterr().err
        # a STALE verdict is ignored: the (failing) probe actually runs
        # and its fresh fallback verdict overwrites the cache
        cache.write_text(json.dumps(
            {"fallback": True,
             "probed_unix_time": _time.time() - 10_000}))
        monkeypatch.setenv("JAX_PLATFORMS", "")
        assert probe_backend_or_fallback(cache_path=str(cache)) is True
        assert calls  # stale cache re-probed
        fresh = json.loads(cache.read_text())
        assert fresh["fallback"] is True
        assert _time.time() - fresh["probed_unix_time"] < 60
        # a fresh HEALTHY verdict never short-circuits: the relay flaps
        # on minute timescales, so only fallback verdicts are reusable —
        # trusting a cached success would reopen the first-touch hang
        cache.write_text(json.dumps(
            {"fallback": False, "probed_unix_time": _time.time()}))
        calls.clear()
        monkeypatch.setenv("JAX_PLATFORMS", "")
        assert probe_backend_or_fallback(cache_path=str(cache)) is True
        assert calls  # healthy cache ignored: the probe ran (and failed)

    def test_train_cli_with_mesh_and_checkpoint(self, tmp_path, capsys):
        import jax

        if len(jax.devices()) < 4:
            pytest.skip("needs 4 fake devices")
        from pertgnn_tpu.cli import train_main

        train_main.main([
            "--synthetic", "--min_traces_per_entry", "10",
            "--synthetic_entries", "3", "--synthetic_traces_per_entry", "30",
            "--artifact_dir", str(tmp_path / "p2"),
            "--epochs", "1", "--batch_size", "8", "--hidden_channels", "8",
            "--data_parallel", "2", "--model_parallel", "2",
            "--checkpoint_dir", str(tmp_path / "ck")])
        out = capsys.readouterr().out
        assert "Epoch: 0" in out
        assert os.path.isdir(str(tmp_path / "ck"))
