"""The reference's TRAINING DRIVER (pert_gnn.py) executes verbatim on
the pyg_shim and its train-time featurization matches ours exactly —
benchmarks/parity/reference_driver_crosscheck.py run at reduced scale.

This is the harness that DISCOVERED the last-stage-copy featurization
quirk (ModelConfig.feature_all_stage_copies docstring); keeping it in
the suite pins both the quirk's faithful default and the driver-level
loss/metric semantics (pinball-as-"Train" ratio ~2 at tau=0.5).
"""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REFERENCE = os.environ.get("PERTGNN_REFERENCE_DIR", "/root/reference")


@pytest.mark.skipif(
    not os.path.isfile(os.path.join(_REFERENCE, "pert_gnn.py")),
    reason="reference checkout not available")
def test_reference_driver_crosscheck():
    out = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "benchmarks", "parity",
                      "reference_driver_crosscheck.py")],
        capture_output=True, text=True, timeout=3000,
        env=dict(os.environ, JAX_PLATFORMS="cpu", DRIVER_EPOCHS="2"))
    assert out.returncode == 0, (out.stdout[-3000:], out.stderr[-2000:])
    verdict = json.loads(out.stdout)
    assert verdict["pass"], verdict
    assert verdict["checks"]["pert_get_x_exact"]
    assert verdict["checks"]["span_get_x_exact"]
    assert verdict["checks"]["pert_magnitude_sane"]
