"""Distributed request tracing + graftscope (ISSUE 12 acceptance):
span nesting invariants as a property over generated request trees
(one root per trace, parents resolve, no cycles), collector clock
alignment on synthetically skewed files, loud orphan refusal, and the
sampling semantics (head decision propagates; the always-keep override
preserves slow exemplars)."""

import json
import os
import time

import pytest

from pertgnn_tpu import telemetry
from pertgnn_tpu.telemetry import (MetricsWriter, TelemetryBus,
                                   load_events)
from pertgnn_tpu.telemetry.tracing import TraceContext, new_span_id
from tools.graftscope import (OrphanSpanError, build_report,
                              chrome_trace_events, collect)
from tools.graftscope.report import check_completeness, percentile


def make_bus(tmp_path, name="tele", rate=1.0, slow_ms=0.0,
             level="trace", **kw):
    writer = MetricsWriter(str(tmp_path / name), **kw)
    return TelemetryBus(writer, level=level, trace_sample_rate=rate,
                        trace_slow_ms=slow_ms), writer


def emit_fleet_trace(bus, t0, *, entry_id=0, requeues=0, worker="w0",
                     outcome="ok", worker_bus=None, skew=0.0):
    """One synthetic fleet-shaped request tree through the real bus
    API, timeline anchored at monotonic t0. ``worker_bus`` (defaults to
    ``bus``) writes the worker-side spans — a second bus stands in for
    a second process; ``skew`` shifts the worker-side clock."""
    worker_bus = worker_bus or bus
    ctx = bus.start_trace()
    t = t0
    for attempt in range(requeues):
        bus.trace_span("trace.router_queue", ctx, t, t + 0.001,
                       worker=worker, attempt=attempt)
        bus.trace_span("trace.transport", ctx, t + 0.001, t + 0.004,
                       worker=worker, outcome="lost")
        t += 0.004
    bus.trace_span("trace.router_queue", ctx, t, t + 0.001,
                   worker=worker, attempt=requeues)
    tsid = bus.trace_span("trace.transport", ctx, t + 0.001, t + 0.009,
                          worker=worker, outcome="ok")
    wctx = worker_bus.adopt_trace(ctx.trace_id, tsid)
    w = t + skew  # the worker stamps on ITS clock
    worker_bus.trace_span("trace.worker_queue", wctx, w + 0.002,
                          w + 0.003, coalesced=1)
    worker_bus.trace_span("trace.pack", wctx, w + 0.003, w + 0.004)
    worker_bus.trace_span("trace.dispatch", wctx, w + 0.004, w + 0.005)
    worker_bus.trace_span("trace.compute", wctx, w + 0.005, w + 0.008)
    bus.trace_span("trace.complete", ctx, t + 0.009, t + 0.010)
    bus.finish_trace("trace.request", ctx, t0, t + 0.010,
                     outcome=outcome, entry_id=entry_id)
    return ctx


class TestSamplingSemantics:
    def test_rate_zero_means_off(self, tmp_path):
        bus, _ = make_bus(tmp_path, rate=0.0)
        assert bus.start_trace() is None

    def test_basic_level_means_off(self, tmp_path):
        bus, _ = make_bus(tmp_path, rate=1.0, level="basic")
        assert bus.start_trace() is None
        assert bus.adopt_trace("t", "p") is None

    def test_rate_one_always_samples(self, tmp_path):
        bus, _ = make_bus(tmp_path, rate=1.0)
        assert all(bus.start_trace().sampled for _ in range(50))

    def test_unsampled_without_slow_keep_is_free(self, tmp_path):
        # nothing could ever flush the buffer -> no context at all
        bus, _ = make_bus(tmp_path, rate=1e-12, slow_ms=0.0)
        assert bus.start_trace() is None

    def test_slow_exemplar_survives_low_sample_rate(self, tmp_path):
        bus, writer = make_bus(tmp_path, rate=1e-12, slow_ms=100.0)
        ctx = bus.start_trace()
        assert ctx is not None and not ctx.sampled
        tm = time.monotonic()
        bus.trace_span("trace.router_queue", ctx, tm, tm + 0.001)
        # 500 ms total >= the 100 ms threshold -> buffered spans flush
        bus.finish_trace("trace.request", ctx, tm, tm + 0.5,
                         outcome="ok", entry_id=1)
        # a FAST unsampled request drops its buffer
        ctx2 = bus.start_trace()
        bus.trace_span("trace.router_queue", ctx2, tm, tm + 0.001)
        bus.finish_trace("trace.request", ctx2, tm, tm + 0.002,
                         outcome="ok", entry_id=2)
        bus.close()
        spans = [e for e in load_events(writer.path)
                 if e["kind"] == "span"]
        assert len(spans) == 2  # slow root + its buffered child only
        root = next(e for e in spans if e["name"] == "trace.request")
        assert root["tags"]["sampled"] == "slow"
        assert root["tags"]["entry_id"] == 1
        child = next(e for e in spans
                     if e["name"] == "trace.router_queue")
        assert child["trace_id"] == root["trace_id"]
        assert child["parent_span_id"] == root["span_id"]

    def test_propagation_only_for_sampled(self, tmp_path):
        bus, _ = make_bus(tmp_path, rate=1e-12, slow_ms=100.0)
        ctx = bus.start_trace()
        assert not ctx.sampled  # the router would NOT propagate this


class TestSpanNesting:
    """Property: whatever mix of requests/requeues/outcomes the fleet
    serves, collected traces have one root each, fully-resolving
    parents, and no cycles."""

    def _check_tree(self, spans):
        by_id = {s.span_id: s for s in spans}
        roots = [s for s in spans if s.parent_id is None]
        assert len(roots) == 1
        for s in spans:
            if s.parent_id is not None:
                assert s.parent_id in by_id, f"orphan {s.name}"
            # walk to the root; a cycle would loop forever, so bound it
            seen = set()
            cur = s
            while cur.parent_id is not None:
                assert cur.span_id not in seen, "cycle"
                seen.add(cur.span_id)
                cur = by_id[cur.parent_id]
            assert cur.parent_id is None

    def test_generated_request_mix(self, tmp_path):
        hyp = pytest.importorskip(
            "hypothesis",
            reason="property tests need the hypothesis dev extra "
                   "(pip install -e .[dev])")
        st = hyp.strategies

        @hyp.settings(max_examples=25, deadline=None)
        @hyp.given(st.lists(
            st.tuples(st.integers(0, 3),            # requeues
                      st.sampled_from(["ok", "error"]),
                      st.booleans()),               # separate worker bus
            min_size=1, max_size=8))
        def run(requests):
            import shutil
            d = tmp_path / "prop"
            shutil.rmtree(d, ignore_errors=True)
            bus, writer = make_bus(d.parent, name="prop", rate=1.0)
            wbus, wwriter = make_bus(d.parent, name="prop", rate=1.0)
            tm = time.monotonic()
            for i, (requeues, outcome, two_proc) in enumerate(requests):
                emit_fleet_trace(
                    bus, tm + i, entry_id=i, requeues=requeues,
                    outcome=outcome,
                    worker_bus=wbus if two_proc else bus)
            bus.close()
            wbus.close()
            result = collect(str(d))
            assert len(result.traces) == len(requests)
            for spans in result.traces.values():
                self._check_tree(spans)
            report = build_report(result)
            assert report["incomplete"] == 0
            assert report["orphans"] == 0
            n_ok = sum(1 for _r, o, _t in requests if o == "ok")
            assert report["traces_ok"] == n_ok
            assert report["traces_error"] == len(requests) - n_ok

        run()


class TestCollector:
    def _write_jsonl(self, path, events):
        with open(path, "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")

    def _span(self, pid, name, tid, sid, psid, tm0, dur_ms, **tags):
        ev = {"v": 2, "t": 1000.0 + tm0, "tm": tm0 + dur_ms / 1e3,
              "pid": pid, "pi": 0, "kind": "span", "name": name,
              "dur_ms": dur_ms, "trace_id": tid, "span_id": sid,
              "tm0": tm0}
        if psid is not None:
            ev["parent_span_id"] = psid
        if tags:
            ev["tags"] = tags
        return ev

    def test_clock_alignment_recovers_synthetic_skew(self, tmp_path):
        """Two hand-written files: the router stamps on one clock, the
        worker on a clock 1000 s ahead. The bounding pairs pin the
        offset; aligned worker spans must land INSIDE the router's
        transport spans."""
        d = tmp_path / "skew"
        os.makedirs(d)
        skew = 1000.0  # worker clock = router clock + 1000 s
        router, worker = [], []
        for i in range(10):
            t = i * 1.0
            tid = f"t{i:02d}"
            router.append(self._span(
                100, "trace.request", tid, f"64.{i}", None, t, 100.0,
                outcome="ok", entry_id=i))
            router.append(self._span(
                100, "trace.router_queue", tid, f"64.q{i}", f"64.{i}",
                t, 10.0))
            router.append(self._span(
                100, "trace.transport", tid, f"64.t{i}", f"64.{i}",
                t + 0.010, 80.0, worker="w0", outcome="ok"))
            w = t + skew + 0.020  # worker work inside the round trip
            for j, stage in enumerate(("worker_queue", "pack",
                                       "dispatch", "compute")):
                worker.append(self._span(
                    200, f"trace.{stage}", tid, f"c8.{i}{j}",
                    f"64.t{i}", w + j * 0.010, 10.0))
            router.append(self._span(
                100, "trace.complete", tid, f"64.c{i}", f"64.{i}",
                t + 0.090, 10.0))
        self._write_jsonl(d / "telemetry-p0-hostA-100.jsonl", router)
        self._write_jsonl(d / "telemetry-p0-hostB-200.jsonl", worker)
        result = collect(str(d))
        rep = result.clock[200]
        # the true offset is -1000 s (worker stamps map DOWN onto the
        # router clock); the pair bounds give +-~20ms slack
        assert rep["offset_ms"] == pytest.approx(-1000e3, abs=50.0)
        assert rep["consistent"] is True
        assert rep["uncertainty_ms"] < 50.0
        assert result.clock[100]["reference"] is True
        for spans in result.traces.values():
            tr = next(s for s in spans if s.name == "trace.transport")
            for s in spans:
                if s.pid == 200:
                    assert s.atm0 >= tr.atm0 - 1e-6
                    assert s.atm1 <= tr.atm1 + 1e-6
        assert check_completeness(result) == []
        report = build_report(result, top_k=2)
        assert report["traces_ok"] == 10
        assert report["stage_ms"]["compute"]["p99_ms"] == \
            pytest.approx(10.0, rel=0.01)
        # exclusive transport: 80 total - 40 worker = 40
        assert report["stage_ms"]["transport"]["p50_ms"] == \
            pytest.approx(40.0, rel=0.01)

    def test_orphan_spans_refused_loudly(self, tmp_path):
        d = tmp_path / "orphan"
        os.makedirs(d)
        evs = [self._span(100, "trace.request", "t0", "64.0", None,
                          0.0, 10.0, outcome="ok"),
               self._span(100, "trace.worker_queue", "t0", "64.1",
                          "missing-parent", 0.0, 1.0)]
        self._write_jsonl(d / "telemetry-p0-h-100.jsonl", evs)
        with pytest.raises(OrphanSpanError, match="missing-parent"):
            collect(str(d))
        result = collect(str(d), allow_orphans=True)
        assert len(result.orphans) == 1

    def test_incomplete_chain_detected(self, tmp_path):
        d = tmp_path / "inc"
        os.makedirs(d)
        evs = [self._span(100, "trace.request", "t0", "64.0", None,
                          0.0, 10.0, outcome="ok"),
               self._span(100, "trace.router_queue", "t0", "64.1",
                          "64.0", 0.0, 1.0),
               self._span(100, "trace.transport", "t0", "64.2", "64.0",
                          1.0, 8.0, outcome="ok")]
        self._write_jsonl(d / "telemetry-p0-h-100.jsonl", evs)
        violations = check_completeness(collect(str(d)))
        assert len(violations) == 1
        assert "worker_queue" in violations[0]

    def test_multi_root_detected(self, tmp_path):
        d = tmp_path / "mr"
        os.makedirs(d)
        evs = [self._span(100, "trace.request", "t0", "64.0", None,
                          0.0, 10.0, outcome="ok"),
               self._span(100, "trace.request", "t0", "64.1", None,
                          0.0, 10.0, outcome="ok")]
        self._write_jsonl(d / "telemetry-p0-h-100.jsonl", evs)
        result = collect(str(d))
        assert result.multi_root == {"t0": 2}
        assert any("2 roots" in v
                   for v in check_completeness(result))

    def test_cli_round_trip(self, tmp_path):
        import subprocess
        import sys
        bus, writer = make_bus(tmp_path, name="cli", rate=1.0)
        tm = time.monotonic()
        for i in range(5):
            emit_fleet_trace(bus, tm + i, entry_id=i)
        bus.close()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        perfetto = str(tmp_path / "out.trace.json")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graftscope",
             "--telemetry_dir", str(tmp_path / "cli"),
             "--assert_complete", "--expect_ok", "5",
             "--perfetto", perfetto],
            capture_output=True, text=True, cwd=repo, timeout=120)
        assert proc.returncode == 0, proc.stderr
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["traces_ok"] == 5 and report["failures"] == []
        with open(perfetto) as f:
            exported = json.load(f)
        assert len(exported["traceEvents"]) == report["spans"]
        # wrong expectation -> nonzero exit, failure named
        proc2 = subprocess.run(
            [sys.executable, "-m", "tools.graftscope",
             "--telemetry_dir", str(tmp_path / "cli"),
             "--expect_ok", "6"],
            capture_output=True, text=True, cwd=repo, timeout=120)
        assert proc2.returncode == 1
        assert "expected 6" in proc2.stdout or "expected 6" in proc2.stderr

    def test_perfetto_events_well_formed(self, tmp_path):
        bus, _ = make_bus(tmp_path, name="pf", rate=1.0)
        tm = time.monotonic()
        emit_fleet_trace(bus, tm)
        bus.close()
        events = chrome_trace_events(collect(str(tmp_path / "pf")))
        assert events and all(e["ph"] == "X" and e["ts"] >= 0
                              and e["dur"] >= 0 for e in events)


class TestRotation:
    def test_rotation_parts_carry_all_events(self, tmp_path):
        writer = MetricsWriter(str(tmp_path / "rot"),
                               rotate_mb=300 / 2 ** 20)  # ~300 bytes
        bus = TelemetryBus(writer, level="trace")
        for i in range(50):
            bus.counter("rot.tick", 1, i=i)
        bus.close()
        files = sorted(os.listdir(tmp_path / "rot"))
        assert len(files) > 1, "no rotation happened"
        assert all(f.endswith(".jsonl") for f in files)
        parts = [f for f in files if ".part" in f]
        assert parts, f"no .partN files in {files}"
        total = 0
        for f in files:
            evs = load_events(str(tmp_path / "rot" / f))
            total += sum(1 for e in evs if e["name"] == "rot.tick")
            if ".part" in f:
                assert evs[0]["name"] == "rotate"
        assert total == 50, "rotation lost events"

    def test_collector_merges_rotated_parts(self, tmp_path):
        writer = MetricsWriter(str(tmp_path / "rotc"),
                               rotate_mb=2000 / 2 ** 20)
        bus = TelemetryBus(writer, level="trace", trace_sample_rate=1.0)
        tm = time.monotonic()
        for i in range(30):
            emit_fleet_trace(bus, tm + i, entry_id=i)
        bus.close()
        assert any(".part" in f
                   for f in os.listdir(tmp_path / "rotc"))
        result = collect(str(tmp_path / "rotc"))
        assert len(result.traces) == 30
        assert build_report(result)["incomplete"] == 0


class TestPercentile:
    def test_matches_linear_interpolation(self):
        vals = sorted(float(v) for v in range(1, 101))
        assert percentile(vals, 50) == pytest.approx(50.5)
        assert percentile(vals, 99) == pytest.approx(99.01)
        assert percentile(vals, 99.9) == pytest.approx(99.901)
        assert percentile([], 50) is None
        assert percentile([7.0], 99) == 7.0


class TestQueueIntegration:
    """The real MicrobatchQueue front door: standalone roots with the
    engine-stage chain, through a live (tiny) engine."""

    @pytest.fixture(scope="class")
    def traced_engine(self, preprocessed, tmp_path_factory):
        from pertgnn_tpu.batching import build_dataset
        from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                        ServeConfig, TrainConfig)
        from pertgnn_tpu.serve.engine import InferenceEngine
        from pertgnn_tpu.train.loop import restore_target_state

        cfg = Config(ingest=IngestConfig(min_traces_per_entry=10),
                     data=DataConfig(max_traces=200, batch_size=16),
                     train=TrainConfig(label_scale=1000.0),
                     serve=ServeConfig(bucket_growth=4.0,
                                       max_graphs_per_batch=4))
        ds = build_dataset(preprocessed, cfg)
        _, state = restore_target_state(ds, cfg)
        writer = MetricsWriter(str(tmp_path_factory.mktemp("qtrace")))
        bus = TelemetryBus(writer, level="trace",
                           trace_sample_rate=1.0)
        engine = InferenceEngine.from_dataset(ds, cfg, state,
                                              bus=bus).warmup()
        yield ds, engine, bus, writer.path
        bus.close()

    def test_standalone_queue_produces_complete_traces(self,
                                                       traced_engine):
        from pertgnn_tpu.serve.queue import MicrobatchQueue

        ds, engine, bus, path = traced_engine
        s = ds.splits["test"]
        with MicrobatchQueue(engine, flush_deadline_ms=5) as q:
            futs = [q.submit(int(s.entry_ids[i]), int(s.ts_buckets[i]))
                    for i in range(4)]
            [f.result(timeout=60) for f in futs]
        bus.flush()
        result = collect(os.path.dirname(path))
        assert len(result.traces) >= 4
        report = build_report(result)
        assert report["incomplete"] == 0, \
            report["completeness_violations"]
        assert report["traces_ok"] >= 4
        # standalone chains: worker stages, no transport legs
        for spans in result.traces.values():
            stages = {sp.stage for sp in spans}
            assert "transport" not in stages
            assert {"worker_queue", "pack", "dispatch",
                    "compute"} <= stages

    def test_adopted_context_suppresses_root(self, traced_engine):
        """A fleet worker's queue (trace_roots=False) must neither
        start roots nor finish adopted ones — the router owns both."""
        from pertgnn_tpu.serve.queue import MicrobatchQueue

        ds, engine, bus, path = traced_engine
        s = ds.splits["test"]
        n_before = sum(
            1 for e in load_events(path)
            if e["kind"] == "span" and e["name"] == "trace.request")
        ctx = bus.adopt_trace("feedcafe00000000", "99.1")
        with MicrobatchQueue(engine, flush_deadline_ms=0,
                             trace_roots=False) as q:
            q.submit(int(s.entry_ids[0]), int(s.ts_buckets[0]),
                     trace=ctx).result(timeout=60)
            # an untraced co-request on the same queue: no context
            q.submit(int(s.entry_ids[1]),
                     int(s.ts_buckets[1])).result(timeout=60)
        bus.flush()
        evs = [e for e in load_events(path) if e["kind"] == "span"]
        n_roots = sum(1 for e in evs if e["name"] == "trace.request")
        assert n_roots == n_before, "worker-side queue emitted a root"
        adopted = [e for e in evs
                   if e.get("trace_id") == "feedcafe00000000"]
        stages = {e["name"] for e in adopted}
        assert {"trace.worker_queue", "trace.pack", "trace.dispatch",
                "trace.compute"} <= stages
        assert all(e["parent_span_id"] == "99.1" for e in adopted)
