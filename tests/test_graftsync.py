"""tools/graftsync: static concurrency verification, run over the real
repo in tier-1 — lock-order cycles, blocking-while-locked, dropped
Future custody, CV-protocol breaks, unnamed/unjoined threads, and
unbounded waits must stay mechanically impossible (docs/LINTS.md).

Fixture tests build miniature repos under tmp_path (graftlint's
Context only needs the path shape); THE gate is
test_repo_syncs_clean, which runs every pass over the live tree
inside a wall-clock budget. Per-pass NEGATIVE fixtures pin that each
pass still detects its planted bug — the repo-wide clean pin cannot
go vacuous — and the justification tables are liveness-pinned: an
entry that no longer suppresses a real finding fails here.
"""

import json
import os
import sys
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graftsync import driver, justify, run_repo  # noqa: E402
from tools.graftsync.cli import main as cli_main  # noqa: E402
from tools.graftsync.passes import get_passes  # noqa: E402

BUDGET_S = 60.0  # the ISSUE-14 acceptance bound; measured ~1 s


def _mini_repo(tmp_path, files: dict[str, str]) -> str:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _run(tmp_path, files, passes=None):
    repo = _mini_repo(tmp_path, files)
    return driver.run_passes(repo, passes, baseline_path="")


# --- THE tier-1 gate -----------------------------------------------------


def test_repo_syncs_clean():
    """Every pass, whole repo, zero NEW violations, under the budget."""
    t0 = time.perf_counter()
    result = run_repo(REPO)
    elapsed = time.perf_counter() - t0
    assert result.new == [], "\n".join(str(v) for v in result.new)
    assert elapsed < BUDGET_S, (
        f"graftsync took {elapsed:.1f}s — over the {BUDGET_S:.0f}s "
        f"budget the ISSUE-14 acceptance pins")


def test_all_six_passes_registered():
    names = [m.RULE for m in get_passes(None)]
    assert names == ["lock-order", "future-lifecycle", "cv-protocol",
                     "thread-lifecycle", "timeout-totality",
                     "ring-protocol"]


def test_justification_tables_are_live():
    """Every (path, key) entry in every graftsync table must still be
    suppressing a REAL finding on the live tree — a dead exemption is
    a hole in the proof with a permission slip. (SINGLE_WRITER's
    liveness is pinned by test_graftlint.py, its consumer.)"""
    result = run_repo(REPO)
    hits = result.justification_hits
    for rule, table in justify.TABLES.items():
        live = hits.get(rule, set())
        dead = set(table) - live
        assert not dead, (
            f"dead {rule} justification entries (the findings they "
            f"suppressed no longer exist — delete them): {sorted(dead)}")


def test_single_writer_is_the_shared_table():
    """The fold satellite: graftlint's lock-discipline ALLOWLIST must
    BE the shared table, not a copy that can drift."""
    from tools.graftlint.passes import lock_discipline

    assert lock_discipline.ALLOWLIST is justify.SINGLE_WRITER


# --- per-pass negative fixtures (the proof cannot go vacuous) -------------


_CYCLE = """
    import threading

    class A:
        def __init__(self):
            self._la = threading.Lock()
            self._lb = threading.Lock()

        def ab(self):
            with self._la:
                with self._lb:
                    pass

        def ba(self):
            with self._lb:
                with self._la:
                    pass
"""


def test_lock_order_detects_cycles(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/fleet/c.py": _CYCLE},
               ["lock-order"])
    assert any("cycle" in v.message for v in res.new), res.new


def test_lock_order_detects_blocking_under_lock(tmp_path):
    src = """
        import threading
        import time

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(1.0)
    """
    res = _run(tmp_path, {"pertgnn_tpu/serve/b.py": src},
               ["lock-order"])
    assert len(res.new) == 1 and "time.sleep" in res.new[0].message


def test_lock_order_sees_through_same_file_calls(tmp_path):
    """The same-file call fixpoint: a helper that blocks, called under
    a lock, is flagged at the locked call site."""
    src = """
        import queue
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def outer(self):
                with self._lock:
                    self.helper()

            def helper(self):
                self._q.get(timeout=1.0)
    """
    res = _run(tmp_path, {"pertgnn_tpu/fleet/h.py": src},
               ["lock-order"])
    assert any("helper" in v.message for v in res.new), res.new


def test_lock_order_condition_wait_on_own_lock_is_fine(tmp_path):
    src = """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._wake = threading.Condition(self._lock)

            def ok(self):
                with self._wake:
                    while self.pending():
                        self._wake.wait(timeout=1.0)

            def pending(self):
                return False
    """
    res = _run(tmp_path, {"pertgnn_tpu/serve/w.py": src},
               ["lock-order"])
    assert res.new == [], res.new


_DROP = """
    class A:
        def __init__(self):
            self._closed = False

        def handoff(self, flight):
            if self._closed:
                return
            self.send(flight)

        def send(self, flight):
            flight.resolve()
"""


def test_future_lifecycle_detects_dropped_custody(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/fleet/d.py": _DROP},
               ["future-lifecycle"])
    assert len(res.new) == 1, res.new
    v = res.new[0]
    assert "flight" in v.message and v.key == "A.handoff:flight"
    # `send` touches flight on its only path — clean


def test_future_lifecycle_empty_guard_is_exempt(tmp_path):
    src = """
        class A:
            def fail_expired(self, expired):
                if not expired:
                    return
                for r in expired:
                    r.future.set_exception(ValueError("x"))
    """
    res = _run(tmp_path, {"pertgnn_tpu/serve/g.py": src},
               ["future-lifecycle"])
    assert res.new == [], res.new


def test_future_lifecycle_detects_dropped_created_future(tmp_path):
    src = """
        from concurrent.futures import Future

        class A:
            def submit(self, closed):
                fut = Future()
                if closed:
                    return None
                self._pending.append(fut)
                return fut
    """
    res = _run(tmp_path, {"pertgnn_tpu/serve/f.py": src},
               ["future-lifecycle"])
    assert len(res.new) == 1 and "escaping" in res.new[0].message


def test_cv_protocol_detects_all_three_breaks(tmp_path):
    src = """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)

            def waiter(self):
                with self._cv:
                    self._cv.wait(timeout=1.0)

            def nudge(self):
                self._cv.notify_all()
    """
    res = _run(tmp_path, {"pertgnn_tpu/serve/cv.py": src},
               ["cv-protocol"])
    msgs = "\n".join(v.message for v in res.new)
    assert "predicate-rechecking loop" in msgs          # wait not in loop
    assert "notify_all()` without holding" in msgs      # unlocked notify


def test_cv_protocol_detects_never_notified(tmp_path):
    src = """
        import threading

        class A:
            def __init__(self):
                self._cv = threading.Condition()

            def waiter(self):
                with self._cv:
                    while True:
                        self._cv.wait(timeout=1.0)
    """
    res = _run(tmp_path, {"pertgnn_tpu/fleet/nn.py": src},
               ["cv-protocol"])
    assert any("NEVER notified" in v.message for v in res.new), res.new


def test_cv_protocol_clean_protocol_passes(tmp_path):
    src = """
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._ready = False

            def waiter(self):
                with self._cv:
                    while not self._ready:
                        self._cv.wait(timeout=1.0)

            def producer(self):
                with self._lock:
                    self._ready = True
                    self._cv.notify_all()
    """
    res = _run(tmp_path, {"pertgnn_tpu/serve/okcv.py": src},
               ["cv-protocol"])
    assert res.new == [], res.new


def test_thread_lifecycle_detects_unnamed_and_unjoined(tmp_path):
    src = """
        import threading

        def orphan():
            t = threading.Thread(target=print)
            t.start()
    """
    res = _run(tmp_path, {"pertgnn_tpu/fleet/t.py": src},
               ["thread-lifecycle"])
    msgs = "\n".join(v.message for v in res.new)
    assert "without `name=`" in msgs and "no reachable `.join()`" in msgs


def test_thread_lifecycle_accepts_named_joined_list(tmp_path):
    src = """
        import threading

        def fan_out(n):
            threads = [threading.Thread(target=print, name=f"w-{i}")
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    """
    res = _run(tmp_path, {"pertgnn_tpu/serve/tl.py": src},
               ["thread-lifecycle"])
    assert res.new == [], res.new


def test_timeout_totality_detects_unbounded_waits(tmp_path):
    src = """
        import queue
        import threading

        class A:
            def __init__(self):
                self._q = queue.SimpleQueue()
                self._t = threading.Thread(target=print, name="x",
                                           daemon=True)

            def drain(self):
                item = self._q.get()
                self._t.join()
                return item
    """
    res = _run(tmp_path, {"pertgnn_tpu/fleet/to.py": src},
               ["timeout-totality"])
    keys = {v.key for v in res.new}
    assert keys == {"A.drain:get@self._q", "A.drain:join@self._t"}, keys


def test_timeout_totality_get_block_positional_is_not_a_timeout(
        tmp_path):
    """Queue.get's FIRST positional is `block`, not a timeout:
    `q.get(True)` is the unbounded wait the pass exists to catch,
    while `q.get(False)` / `q.get(True, 1.0)` are bounded."""
    src = """
        import queue

        class A:
            def __init__(self):
                self._q = queue.Queue()

            def bad(self):
                return self._q.get(True)

            def fine(self):
                self._q.get(False)
                return self._q.get(True, 1.0)
    """
    res = _run(tmp_path, {"pertgnn_tpu/fleet/qb.py": src},
               ["timeout-totality"])
    assert {v.key for v in res.new} == {"A.bad:get@self._q"}, res.new


def test_timeout_totality_explicit_none_timeout_is_unbounded(
        tmp_path):
    """`wait(timeout=None)` / `result(None)` spell out unboundedness —
    they must not count as a bound (review fix)."""
    src = """
        import threading

        class A:
            def __init__(self):
                self._cv = threading.Condition()

            def bad(self, fut):
                with self._cv:
                    while True:
                        self._cv.wait(timeout=None)
                return fut.result(None)
    """
    res = _run(tmp_path, {"pertgnn_tpu/serve/tn.py": src},
               ["timeout-totality"])
    keys = {v.key for v in res.new}
    assert keys == {"A.bad:wait@self._cv", "A.bad:result@fut"}, res.new


def test_lock_order_nonblocking_queue_ops_under_lock_are_fine(
        tmp_path):
    """`get(block=False)` / `get_nowait` never wait — a lock-held
    drain loop using them must not be flagged (review fix)."""
    src = """
        import queue
        import threading

        class A:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def drain(self):
                with self._lock:
                    while True:
                        self._q.get(False)
                        self._q.get(block=False)
    """
    res = _run(tmp_path, {"pertgnn_tpu/fleet/nb.py": src},
               ["lock-order"])
    assert res.new == [], res.new


def test_cv_protocol_justification_table_is_consulted(tmp_path,
                                                      monkeypatch):
    """Every pass must honor its justify table — cv-protocol
    included (review fix: it silently did not)."""
    src = """
        import threading

        class A:
            def __init__(self):
                self._cv = threading.Condition()

            def waiter(self):
                with self._cv:
                    while True:
                        self._cv.wait(timeout=1.0)
    """
    repo = _mini_repo(tmp_path, {"pertgnn_tpu/fleet/nn.py": src})
    first = driver.run_passes(repo, ["cv-protocol"], baseline_path="")
    assert len(first.new) == 1
    monkeypatch.setitem(justify.CV_PROTOCOL,
                        ("pertgnn_tpu/fleet/nn.py", first.new[0].key),
                        "test: deliberately timeout-driven")
    second = driver.run_passes(repo, ["cv-protocol"], baseline_path="")
    assert second.new == []
    assert (("pertgnn_tpu/fleet/nn.py", first.new[0].key)
            in second.justification_hits.get("cv-protocol", set()))


def test_timeout_totality_dict_get_is_not_a_queue(tmp_path):
    src = """
        class A:
            def lookup(self, d):
                return d.get("k")
    """
    res = _run(tmp_path, {"pertgnn_tpu/serve/dg.py": src},
               ["timeout-totality"])
    assert res.new == [], res.new


# --- driver mechanics -----------------------------------------------------


def test_pragma_suppresses_on_the_line(tmp_path):
    fixed = _DROP.replace(
        "def handoff(self, flight):",
        "def handoff(self, flight):"
        "  # graftsync: allow-future-lifecycle")
    res = _run(tmp_path, {"pertgnn_tpu/fleet/d.py": fixed},
               ["future-lifecycle"])
    assert res.new == [], res.new


def test_baseline_accepts_known_debt(tmp_path):
    repo = _mini_repo(tmp_path, {"pertgnn_tpu/fleet/d.py": _DROP})
    first = driver.run_passes(repo, ["future-lifecycle"],
                              baseline_path="")
    assert len(first.new) == 1
    baseline = tmp_path / "baseline.json"
    driver.write_baseline(str(baseline), first.new)
    second = driver.run_passes(repo, ["future-lifecycle"],
                               baseline_path=str(baseline))
    assert second.new == [] and len(second.baselined) == 1


def test_no_baseline_file_in_tree():
    """The tree verifies clean with NO baseline file — the baseline is
    for emergencies, not a parking lot (graftlint's discipline)."""
    assert not os.path.exists(driver.DEFAULT_BASELINE)


def test_cli_exit_codes_and_json(tmp_path, capsys):
    repo = _mini_repo(tmp_path, {"pertgnn_tpu/fleet/c.py": _CYCLE})
    assert cli_main(["lock-order", "--root", repo,
                     "--no-baseline", "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is False and len(doc["violations"]) >= 1
    clean = _mini_repo(tmp_path / "clean",
                       {"pertgnn_tpu/ok.py": "x = 1\n"})
    assert cli_main(["--root", clean, "--no-baseline"]) == 0
    assert cli_main(["no-such-pass", "--root", clean]) == 2
    capsys.readouterr()


def test_cli_write_baseline_roundtrip(tmp_path, capsys):
    repo = _mini_repo(tmp_path, {"pertgnn_tpu/fleet/c.py": _CYCLE})
    baseline = str(tmp_path / "b.json")
    assert cli_main(["lock-order", "--root", repo,
                     "--baseline", baseline, "--write-baseline"]) == 0
    assert cli_main(["lock-order", "--root", repo,
                     "--baseline", baseline]) == 0
    capsys.readouterr()


# --- bench.py --gate refusal ----------------------------------------------


def test_bench_gate_refuses_sync_failing_tree(tmp_path, monkeypatch,
                                              capsys):
    import bench
    import tools.graftsync as gs

    fake = driver.LintResult(
        new=[driver.Violation(rule="lock-order", path="x.py", line=1,
                              message="cycle boom")],
        baselined=[], elapsed_s=0.0, passes=["lock-order"])
    monkeypatch.setattr(gs, "run_repo", lambda repo: fake)
    # graftlint must PASS for the gate to reach the graftsync check
    import tools.graftlint as gl
    clean = driver.LintResult(new=[], baselined=[], elapsed_s=0.0,
                              passes=[])
    monkeypatch.setattr(gl, "run_repo", lambda repo: clean)
    result = tmp_path / "result.json"
    result.write_text(json.dumps({"backend": "cpu", "value": 1.0,
                                  "attention_impl": "segment"}))
    rc = bench.gate_main([str(result)])
    out = capsys.readouterr().out
    assert rc == 1 and "graftsync" in out and "cycle boom" in out


def test_bench_gate_skip_sync_env_is_loud(monkeypatch, capsys):
    import bench

    monkeypatch.setenv("BENCH_GATE_SKIP_SYNC", "1")
    assert bench._graftsync_refusal() == []
    err = capsys.readouterr().err
    assert "BENCH_GATE_SKIP_SYNC" in err


# --- ring-protocol (graftwire shm ring publication discipline) ------------


_RING_OK = """
    class R:
        def try_push(self, off, payload, seq):
            self._payload_write(off, payload)
            self._seq_write(off, seq)

        def try_pop(self, off, n):
            seq = self._seq_read(off)
            payload = self._payload_read(off, n)
            if self._seq_read(off) != seq:
                return None
            return payload
"""


def test_ring_protocol_accepts_the_real_discipline(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/fleet/r.py": _RING_OK},
               ["ring-protocol"])
    assert res.new == [], res.new


def test_ring_protocol_detects_publication_before_payload(tmp_path):
    src = """
        class R:
            def try_push(self, off, payload, seq):
                self._seq_write(off, seq)
                self._payload_write(off, payload)
    """
    res = _run(tmp_path, {"pertgnn_tpu/fleet/r.py": src},
               ["ring-protocol"])
    assert len(res.new) == 1 and "COMMIT" in res.new[0].message


def test_ring_protocol_detects_missing_validate(tmp_path):
    src = """
        class R:
            def try_pop(self, off, n):
                payload = self._payload_read(off, n)
                self._seq_read(off)
                return payload
    """
    res = _run(tmp_path, {"pertgnn_tpu/fleet/r.py": src},
               ["ring-protocol"])
    assert any("preceding _seq_read" in v.message for v in res.new), \
        res.new


def test_ring_protocol_detects_missing_revalidate(tmp_path):
    src = """
        class R:
            def try_pop(self, off, n):
                seq = self._seq_read(off)
                return self._payload_read(off, n)
    """
    res = _run(tmp_path, {"pertgnn_tpu/fleet/r.py": src},
               ["ring-protocol"])
    assert any("re-read" in v.message for v in res.new), res.new


def test_lock_order_flags_ring_call_under_lock(tmp_path):
    """A blocking ring round trip under a held lock is the same bug as
    an HTTP post under a lock — every thread contending for the lock
    stalls for the full transport timeout."""
    src = """
        import threading

        from pertgnn_tpu.fleet.shmring import RingClient

        class A:
            def __init__(self, advert):
                self._lock = threading.Lock()
                self._ring = RingClient(advert)

            def bad(self, payload):
                with self._lock:
                    return self._ring.call(payload, 1.0)
    """
    res = _run(tmp_path, {"pertgnn_tpu/fleet/a.py": src},
               ["lock-order"])
    assert any("ring transport" in v.message for v in res.new), res.new
