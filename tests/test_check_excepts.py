"""tools/check_excepts.py: the no-silently-swallowed-exceptions lint,
run over the real package in tier-1 — the reference's bare-except
pattern (errors eaten, run "succeeds") must not be re-introducible.
"""

import os
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_excepts  # noqa: E402


def _lint(tmp_path, source: str) -> list[str]:
    path = tmp_path / "mod.py"
    path.write_text(textwrap.dedent(source))
    return check_excepts.check_file(str(path))


def test_package_is_clean():
    """THE gate: every except in pertgnn_tpu/ logs, counts, re-raises,
    or carries an explicit reviewable pragma."""
    violations = check_excepts.check_tree(os.path.join(REPO, "pertgnn_tpu"))
    assert violations == []


def test_benchmarks_and_bench_are_clean():
    """The benchmarks are EXIT-CODE ORACLES (pipeline/chaos/coldstart
    assert invariants in the return code) — a swallowed exception there
    forges a green result, so they are lint scope too (ISSUE 5)."""
    violations = []
    for root in check_excepts.default_roots(REPO):
        violations.extend(check_excepts.check_tree(root)
                          if os.path.isdir(root)
                          else check_excepts.check_file(root))
    assert violations == []


def test_default_scope_covers_benchmark_oracles():
    roots = check_excepts.default_roots(REPO)
    names = {os.path.basename(r) for r in roots}
    assert "pertgnn_tpu" in names and "bench.py" in names
    assert "pipeline_bench.py" in names and "chaos_bench.py" in names
    # fleet_bench is an exit-code oracle too (ISSUE 7)
    assert "fleet_bench.py" in names
    # the vendored parity shim mimics a third-party API — out of scope
    assert not any("parity" in r for r in roots)


def test_default_scope_covers_fleet():
    """ISSUE 7: the fleet package (router/transport/policy — the
    zero-lost-Futures invariant lives there) rides the pertgnn_tpu/
    default root, and is itself clean. Pinned explicitly so a future
    scope regression (e.g. an exclusion list) cannot silently drop
    it."""
    fleet = os.path.join(REPO, "pertgnn_tpu", "fleet")
    assert os.path.isdir(fleet)
    in_scope = any(os.path.basename(r) == "pertgnn_tpu"
                   for r in check_excepts.default_roots(REPO))
    assert in_scope
    assert check_excepts.check_tree(fleet) == []


def test_bare_except_is_flagged(tmp_path):
    out = _lint(tmp_path, """
        try:
            x()
        except:
            pass
    """)
    assert len(out) == 1 and "bare `except:`" in out[0]


def test_silent_broad_swallow_is_flagged(tmp_path):
    out = _lint(tmp_path, """
        try:
            x()
        except Exception:
            y = 1
    """)
    assert len(out) == 1 and "swallows silently" in out[0]


def test_logged_counted_or_reraised_passes(tmp_path):
    assert _lint(tmp_path, """
        import logging
        log = logging.getLogger(__name__)
        try:
            x()
        except Exception:
            log.warning("x failed")
        try:
            x()
        except Exception as e:
            bus.counter("x.failed")
        try:
            x()
        except Exception:
            raise RuntimeError("wrapped")
    """) == []


def test_narrow_except_is_allowed_silent(tmp_path):
    # the rule targets BROAD catches; a typed except may stay quiet
    assert _lint(tmp_path, """
        try:
            x()
        except KeyError:
            pass
    """) == []


def test_tracing_helper_counts_as_trace(tmp_path):
    """The ops/ kernel-fallback pattern (ISSUE 6): the handler delegates
    to a same-module helper that owns the log + telemetry counter
    (models/layers._count_kernel_fallback). The delegation must satisfy
    the lint — one helper keeps every fallback site's trace consistent."""
    assert _lint(tmp_path, """
        import logging
        log = logging.getLogger(__name__)

        def _count_fallback(impl, reason):
            log.warning("%s fell back (%s)", impl, reason)
            bus.counter("model.kernel_fallback", impl=impl)

        try:
            x()
        except Exception:
            _count_fallback("pallas", "unavailable")
    """) == []


def test_non_tracing_helper_is_still_flagged(tmp_path):
    """Delegating to a helper that itself stays silent is still a
    swallow — the helper must actually log/count, not just exist."""
    out = _lint(tmp_path, """
        def _quiet(impl):
            return impl

        try:
            x()
        except Exception:
            _quiet("pallas")
    """)
    assert len(out) == 1 and "swallows silently" in out[0]


def test_ops_fallback_sites_carry_the_helper_trace():
    """The kernel-fallback surface specifically (ISSUE 6): ops/ and the
    layer that selects impls are in the default scope AND currently
    clean — a silently-swallowing Pallas-unavailable fallback cannot
    land."""
    for rel in ("pertgnn_tpu/ops", "pertgnn_tpu/models"):
        target = os.path.join(REPO, rel)
        assert check_excepts.check_tree(target) == []
    # the real fallback helper is recognized as a tracer
    import ast

    with open(os.path.join(REPO, "pertgnn_tpu/models/layers.py")) as f:
        tree = ast.parse(f.read())
    assert "_count_kernel_fallback" in check_excepts._trace_helpers(tree)


def test_pragma_exempts_deliberately(tmp_path):
    assert _lint(tmp_path, """
        try:
            x()
        except Exception:  # lint: allow-silent-except
            pass
    """) == []


def test_cli_entry_point(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    x()\nexcept:\n    pass\n")
    assert check_excepts.main([str(bad)]) == 1
    assert "bare `except:`" in capsys.readouterr().out
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert check_excepts.main([str(good)]) == 0
