"""End-to-end training tests on synthetic data (SURVEY.md §4 Integration).

The synthetic generator builds real signal into the labels
(entry_base * pattern_mult * (1 + 0.8*cpu(entry_ms, bucket)) + noise), so a
working model must reduce the loss substantially within a few epochs.
"""

import numpy as np
import jax
import pytest

from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import Config, DataConfig, IngestConfig, ModelConfig, TrainConfig
from pertgnn_tpu.train.loop import fit, evaluate, make_eval_step


@pytest.fixture(scope="module", params=["span", "pert"])
def trained(request, preprocessed):
    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=200, batch_size=16),
        model=ModelConfig(hidden_channels=16, num_layers=2),
        train=TrainConfig(lr=1e-2, epochs=15, label_scale=1000.0),
        graph_type=request.param,
    )
    ds = build_dataset(preprocessed, cfg)
    state, history = fit(ds, cfg)
    return ds, cfg, state, history


class TestTraining:
    def test_loss_decreases(self, trained):
        _, _, _, history = trained
        first, last = history[0], history[-1]
        assert last["train_qloss"] < 0.5 * first["train_qloss"], (
            f"train qloss {first['train_qloss']} -> {last['train_qloss']}")

    def test_metrics_finite(self, trained):
        _, _, _, history = trained
        for row in history:
            for k, v in row.items():
                assert np.isfinite(v), (k, v)

    def test_eval_counts_match_split_sizes(self, trained):
        ds, cfg, state, _ = trained
        from pertgnn_tpu.models.pert_model import make_model
        model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                           ds.num_interfaces, ds.num_rpctypes)
        es = make_eval_step(model, cfg)
        for split in ("valid", "test"):
            m = evaluate(es, state, ds.batches(split))
            assert m["count"] == len(ds.splits[split])

    def test_predictions_track_labels(self, trained):
        """The model must FIT seen data well (train MAPE).

        Generalization to the test split is structurally weak here by design:
        the reference's positional entry-grouped split (pert_gnn.py:196-210)
        puts mostly-unseen entries in the tail splits, and with 3 synthetic
        entries that is degenerate — unseen entry embeddings are random."""
        _, _, _, history = trained
        train_mape = history[-1]["train_mape"]
        assert train_mape < 0.3, f"train MAPE {train_mape}"


def test_materialize_device_matches_host(preprocessed):
    """materialize_device must be the exact twin of materialize_host."""
    from pertgnn_tpu.batching.arena import materialize_host
    from pertgnn_tpu.batching.materialize import (
        build_device_arenas, materialize_device)
    cfg = Config(ingest=IngestConfig(min_traces_per_entry=10),
                 data=DataConfig(max_traces=150, batch_size=8))
    ds = build_dataset(preprocessed, cfg)
    dev = build_device_arenas(ds.arena(), ds.feat_arena())
    mat = jax.jit(lambda i: materialize_device(dev, i))
    for split in ("train", "valid"):
        for idx in ds.index_batches(split):
            got = mat(idx)
            want = materialize_host(ds.arena(), ds._feat_arena(split), idx)
            for name in want._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(got, name)), getattr(want, name),
                    err_msg=f"{split}:{name}")


def test_compact_expansion_matches_host_indices(preprocessed):
    """Device-side expansion of O(graphs) CompactBatch recipes must
    reproduce the host-built IndexBatch stream EXACTLY, field for field
    (same greedy assignment -> same gather indices -> same batches)."""
    from pertgnn_tpu.batching.materialize import (build_device_arenas,
                                                  expand_compact)

    cfg = Config(ingest=IngestConfig(min_traces_per_entry=10),
                 data=DataConfig(max_traces=150, batch_size=8))
    ds = build_dataset(preprocessed, cfg)
    dev = build_device_arenas(ds.arena(), ds.feat_arena())
    exp = jax.jit(lambda c: expand_compact(dev, c, ds.budget.max_nodes,
                                           ds.budget.max_edges))
    for split in ("train", "valid"):
        n = 0
        for cb, idx in zip(ds.compact_batches(split),
                           ds.index_batches(split)):
            got = exp(cb)
            for name in idx._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(got, name)), getattr(idx, name),
                    err_msg=f"{split}:{name}")
            n += 1
        assert n > 1

    # shuffled epochs expand identically too
    for cb, idx in zip(ds.compact_batches("train", shuffle=True, seed=3),
                       ds.index_batches("train", shuffle=True, seed=3)):
        got = exp(cb)
        for name in idx._fields:
            np.testing.assert_array_equal(np.asarray(getattr(got, name)),
                                          getattr(idx, name), err_msg=name)


@pytest.mark.parametrize("scan_chunk", [1, 4])
def test_indexed_fit_matches_host_packed(preprocessed, scan_chunk):
    """fit() with device materialization must reproduce the host-packed
    path's training trajectory (same batches, same numerics)."""
    import dataclasses
    base = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=150, batch_size=8),
        model=ModelConfig(hidden_channels=8, num_layers=2),
        train=TrainConfig(lr=1e-2, epochs=2, label_scale=1000.0,
                          scan_chunk=scan_chunk, device_materialize=True),
    )
    host_cfg = base.replace(train=dataclasses.replace(
        base.train, device_materialize=False))
    _, hist_idx = fit(build_dataset(preprocessed, base), base)
    _, hist_host = fit(build_dataset(preprocessed, host_cfg), host_cfg)
    for ri, rh in zip(hist_idx, hist_host):
        for k in ("train_qloss", "train_mae", "valid_mae", "test_mae"):
            np.testing.assert_allclose(ri[k], rh[k], rtol=1e-5,
                                       err_msg=k)


@pytest.mark.parametrize("scan_chunk", [1, 4])
def test_staged_epoch_recipes_match_streamed(preprocessed, scan_chunk):
    """Epoch-level recipe staging (one H2D per field per epoch, device-side
    per-chunk slicing) must reproduce the per-chunk-transfer trajectory
    exactly — it only changes WHERE the slice happens (VERDICT r4 #2)."""
    import dataclasses
    base = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=150, batch_size=8),
        model=ModelConfig(hidden_channels=8, num_layers=2),
        train=TrainConfig(lr=1e-2, epochs=2, label_scale=1000.0,
                          scan_chunk=scan_chunk, device_materialize=True,
                          stage_epoch_recipes=True),
    )
    streamed = base.replace(train=dataclasses.replace(
        base.train, stage_epoch_recipes=False))
    _, hist_staged = fit(build_dataset(preprocessed, base), base)
    _, hist_stream = fit(build_dataset(preprocessed, streamed), streamed)
    for rs, rt in zip(hist_staged, hist_stream):
        for k in ("train_qloss", "train_mae", "valid_mae", "test_mae"):
            assert rs[k] == rt[k], (k, rs[k], rt[k])


def test_arena_budget_fallback(preprocessed, caplog):
    """Oversized arenas must fall back to host-packed streaming with a
    warning rather than OOM the chip (arena_hbm_budget_gb gate)."""
    import dataclasses
    import logging

    from pertgnn_tpu.train.loop import _resolve_device_materialize

    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=120, batch_size=8),
        model=ModelConfig(hidden_channels=8),
        train=TrainConfig(epochs=1, label_scale=1000.0),
    )
    ds = build_dataset(preprocessed, cfg)
    assert _resolve_device_materialize(ds, cfg) is True

    tiny = cfg.replace(train=dataclasses.replace(cfg.train,
                                                 arena_hbm_budget_gb=0.0))
    # setup_logging() (run by earlier CLI tests) sets propagate=False on
    # the package logger; caplog listens on root — re-enable for the check
    pkg = logging.getLogger("pertgnn_tpu")
    prev = pkg.propagate
    pkg.propagate = True
    try:
        with caplog.at_level(logging.WARNING,
                             logger="pertgnn_tpu.train.loop"):
            assert _resolve_device_materialize(ds, tiny) is False
    finally:
        pkg.propagate = prev
    assert any("falling back to host-packed" in r.message
               for r in caplog.records)
    # fit still trains end-to-end through the fallback
    _, history = fit(ds, tiny, epochs=1)
    assert np.isfinite(history[-1]["train_qloss"])

    unlimited = cfg.replace(train=dataclasses.replace(
        cfg.train, arena_hbm_budget_gb=None))
    assert _resolve_device_materialize(ds, unlimited) is True


def test_local_loss_weight_trains_local_head(preprocessed):
    """local_loss_weight > 0 wires the per-node local head into the loss
    (the reference computes local_pred but never trains it — SURVEY §2.3;
    this is the surfaced capability option). The auxiliary term must
    change the loss and actually train the head."""
    import jax

    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import _loss_fn, create_train_state

    cfg0 = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=150, batch_size=8),
        model=ModelConfig(hidden_channels=8, num_layers=2),
        train=TrainConfig(lr=1e-2, epochs=2, label_scale=1000.0),
    )
    cfg1 = cfg0.replace(model=ModelConfig(hidden_channels=8, num_layers=2,
                                          local_loss_weight=0.5))
    ds = build_dataset(preprocessed, cfg0)
    import optax

    model = make_model(cfg0.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    batch = jax.tree.map(jax.numpy.asarray, next(ds.batches("train")))
    state = create_train_state(model, optax.adam(1e-2), batch, 0)
    rng = jax.random.PRNGKey(0)

    def loss_and_head_grad(cfg):
        loss, _ = _loss_fn(model, cfg, state.params, state.batch_stats,
                           batch, rng)
        g = jax.grad(lambda p: _loss_fn(model, cfg, p, state.batch_stats,
                                        batch, rng)[0])(state.params)
        return float(loss), np.abs(
            np.asarray(g["local_head"]["kernel"])).max()

    l0, g0 = loss_and_head_grad(cfg0)
    l1, g1 = loss_and_head_grad(cfg1)
    assert l1 > l0                      # aux pinball term added
    assert g0 == 0.0 and g1 > 0.0      # head only trains when weighted

    # and fit() runs end-to-end with the aux loss on
    _, history = fit(ds, cfg1, epochs=2)
    assert np.isfinite(history[-1]["train_qloss"])
    assert history[1]["train_qloss"] < history[0]["train_qloss"]


def test_fit_deterministic_same_seed(preprocessed):
    """Two fit() runs with identical config+seed produce identical
    per-epoch metrics (host packing, shuffling, and the jitted step are
    all deterministic on a fixed backend)."""
    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=150, batch_size=8),
        model=ModelConfig(hidden_channels=8, num_layers=2),
        train=TrainConfig(lr=1e-2, epochs=2, label_scale=1000.0,
                          scan_chunk=4),
    )
    _, h1 = fit(build_dataset(preprocessed, cfg), cfg)
    _, h2 = fit(build_dataset(preprocessed, cfg), cfg)
    for r1, r2 in zip(h1, h2):
        for k in ("train_qloss", "train_mae", "valid_mae", "test_mae"):
            assert r1[k] == r2[k], (k, r1[k], r2[k])


def test_eval_deterministic(preprocessed):
    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=120, batch_size=8),
        model=ModelConfig(hidden_channels=8),
        train=TrainConfig(epochs=1),
    )
    ds = build_dataset(preprocessed, cfg)
    state, _ = fit(ds, cfg)
    from pertgnn_tpu.models.pert_model import make_model
    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    es = make_eval_step(model, cfg)
    a = evaluate(es, state, ds.batches("valid"))
    b = evaluate(es, state, ds.batches("valid"))
    assert a == b


def test_staged_recipes_byte_cap_falls_back_per_chunk(preprocessed, caplog):
    """stage_recipes_max_mb (ADVICE r4): a staged epoch bigger than the
    cap must warn and fall back to per-chunk transfers through the SAME
    put path — with an identical training trajectory."""
    import dataclasses
    import logging

    base = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=150, batch_size=8),
        model=ModelConfig(hidden_channels=8, num_layers=2),
        train=TrainConfig(lr=1e-2, epochs=2, label_scale=1000.0,
                          scan_chunk=2, device_materialize=True,
                          stage_epoch_recipes=True),
    )
    capped = base.replace(train=dataclasses.replace(
        base.train, stage_recipes_max_mb=1e-6))  # ~1 byte: always exceeded
    _, hist_staged = fit(build_dataset(preprocessed, base), base)
    with caplog.at_level(logging.WARNING, logger="pertgnn_tpu.train.loop"):
        _, hist_capped = fit(build_dataset(preprocessed, capped), capped)
    assert any("falling back to per-chunk transfers" in r.message
               for r in caplog.records)
    for rs, rc in zip(hist_staged, hist_capped):
        for k in ("train_qloss", "train_mae", "valid_mae", "test_mae"):
            assert rs[k] == rc[k], (k, rs[k], rc[k])


def test_fit_empty_train_split_raises_clearly(preprocessed):
    """A corpus whose filters leave so few examples that the positional
    60/20/20 split gives train ZERO graphs (n=1: edges [0,0,0,1]) must
    fail with an actionable message, not a bare StopIteration from the
    sample probe or a TypeError from the metric sums."""
    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=1, batch_size=4),
        model=ModelConfig(hidden_channels=8),
        train=TrainConfig(epochs=1, label_scale=1000.0),
    )
    ds = build_dataset(preprocessed, cfg)
    assert len(ds.splits["train"]) == 0  # the scenario under test
    with pytest.raises(ValueError, match="train split is empty"):
        fit(ds, cfg)
