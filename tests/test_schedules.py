"""pertgnn_tpu/testing/schedules.py — the deterministic interleaving
harness (ISSUE 14), and the three nastiest fleet races driven in BOTH
orders through it with bit-identical, exactly-once resolution
asserted:

1. hedge-settle vs. primary-answer (the PR-13 race, now
   scheduler-driven instead of hand-built from Events);
2. autoscale ``remove_worker`` vs. an in-flight dispatch (the
   ``_assign``→sender handoff window the membership re-check closes —
   driven through the router's ``fleet.assign.handoff`` sync points);
3. drain vs. queue close on the worker-side MicrobatchQueue.

Plus the harness's own contract: scripts are enforced orders,
unscripted points pass through, an undeliverable script raises
ScheduleTimeout instead of hanging, and — the seeded property test —
a planted LOST-WAKEUP bug in a toy two-thread custody protocol is
reproduced or avoided deterministically by the scripted order.
"""

import threading
import time
from types import SimpleNamespace

import pytest

from pertgnn_tpu.config import FleetConfig, ServeConfig
from pertgnn_tpu.fleet.router import FleetRouter
from pertgnn_tpu.serve.errors import QueueClosed
from pertgnn_tpu.serve.queue import MicrobatchQueue
from pertgnn_tpu.testing import schedules
from pertgnn_tpu.testing.schedules import (ScheduleTimeout,
                                           ScriptedScheduler)


@pytest.fixture(autouse=True)
def _no_leaked_scheduler():
    yield
    schedules.uninstall()


# -- 1. the harness itself -------------------------------------------------


class TestScriptedScheduler:
    def test_enforces_the_scripted_order_across_threads(self):
        for script in (["a", "b"], ["b", "a"]):
            order: list[str] = []
            sched = ScriptedScheduler(script, timeout_s=5.0)

            def hit(name):
                sched.point(name)
                order.append(name)

            with sched:
                ts = [threading.Thread(target=hit, args=(n,),
                                       name=f"sched-{n}")
                      for n in ("a", "b")]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=5.0)
            assert order == script
            assert sched.trace == script and sched.finished()

    def test_unscripted_points_pass_through(self):
        sched = ScriptedScheduler(["x"], timeout_s=5.0)
        with sched:
            t0 = time.perf_counter()
            sched.point("free")          # not in the script: no block
            assert time.perf_counter() - t0 < 1.0
            sched.point("x")
        assert sched.passed == ["free"] and sched.trace == ["x"]

    def test_consumed_entries_free_later_occurrences(self):
        sched = ScriptedScheduler(["p"], timeout_s=5.0)
        with sched:
            sched.point("p")     # consumed
            sched.point("p")     # second occurrence: pass-through
        assert sched.trace == ["p"] and sched.passed == ["p"]

    def test_undeliverable_script_raises_instead_of_hanging(self):
        sched = ScriptedScheduler(["never", "late"], timeout_s=0.2)
        with pytest.raises(ScheduleTimeout):
            sched.point("late")  # "never" is never delivered
        # the broken flag propagates: every later point fails fast
        with pytest.raises(ScheduleTimeout):
            sched.point("never")

    def test_sync_point_is_free_without_a_scheduler(self):
        assert schedules.active() is None
        schedules.sync_point("anything")  # must not raise or block


# -- 2. the seeded lost-wakeup property (hypothesis satellite) -------------


def _lost_wakeup_trial(producer_first: bool) -> tuple[bool, list[str]]:
    """A toy two-thread custody protocol with a PLANTED bug: the
    consumer waits UNCONDITIONALLY (no predicate loop — exactly what
    graftsync's cv-protocol pass flags), so a notify that fires before
    the consumer reaches wait() is lost and the wait times out. The
    scripted order decides the outcome deterministically."""
    cv = threading.Condition()
    woken: dict = {}

    def consumer():
        schedules.sync_point("consume.start")
        with cv:
            schedules.sync_point("consume.locked")
            woken["v"] = cv.wait(timeout=0.4)   # the planted bug

    def producer():
        schedules.sync_point("produce.go")
        with cv:
            cv.notify_all()
        schedules.sync_point("produce.done")

    script = (["produce.go", "produce.done", "consume.start"]
              if producer_first else
              ["consume.locked", "produce.go", "produce.done"])
    sched = ScriptedScheduler(script, timeout_s=10.0)
    with sched:
        ts = [threading.Thread(target=consumer, name="toy-consumer"),
              threading.Thread(target=producer, name="toy-producer")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10.0)
    assert sched.finished(), sched.trace
    return woken["v"], sched.trace


def test_lost_wakeup_reproduced_by_order():
    woken, _trace = _lost_wakeup_trial(producer_first=True)
    assert woken is False      # the notify fired first: wakeup LOST
    woken, _trace = _lost_wakeup_trial(producer_first=False)
    assert woken is True       # waiter first: wakeup delivered


def test_schedule_permutation_property():
    """Seeded permutations: the scheduler explores DISTINCT orders
    (the consumed trace equals the script) and the planted bug's
    reproduction is a pure function of the order."""
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=12, deadline=None)
    @given(st.booleans())
    def prop(producer_first):
        woken, trace = _lost_wakeup_trial(producer_first)
        expected = (["produce.go", "produce.done", "consume.start"]
                    if producer_first else
                    ["consume.locked", "produce.go", "produce.done"])
        assert trace == expected
        assert woken is (not producer_first)

    prop()


# -- 3. race 1: hedge-settle vs. primary-answer ----------------------------


def _probe_200(base_url, timeout_s):
    return 200, {}


def _mk_router(urls, post, cfg):
    return FleetRouter(urls, lambda eid: (10, 10), (8, 10_000, 10_000),
                       cfg=cfg, transport_post=post,
                       transport_probe=_probe_200)


HEDGE_CFG = FleetConfig(hedge_quantile_ms=30.0,
                        router_flush_deadline_ms=0.0,
                        health_poll_interval_s=60.0,
                        dispatch_timeout_s=10.0)


def _race_hedge(hedge_wins: bool) -> float:
    calls: list[str] = []
    calls_lock = threading.Lock()

    def post(base_url, entries, ts, timeout_s, trace=None, slo=None,
             dg=None):
        with calls_lock:
            calls.append(base_url)
            nth = len(calls)
        # leg identity by dispatch order: the first post is always the
        # primary (the hedger only fires 30ms later). The primary is
        # parked at its reply point in BOTH scripts until the hedge
        # leg has arrived — that is what MAKES it a straggler — and
        # "settled" (the winner's done-callback) strictly orders the
        # loser's answer after exactly-once resolution.
        if nth == 1:
            schedules.sync_point("primary.reply")
        else:
            schedules.sync_point("hedge.arrived")
            schedules.sync_point("hedge.reply")
        return [{"pred": float(e) * 2.0} for e in entries]

    script = (["hedge.arrived", "hedge.reply", "settled",
               "primary.reply"] if hedge_wins
              else ["hedge.arrived", "primary.reply", "settled",
                    "hedge.reply"])
    sched = ScriptedScheduler(script, timeout_s=15.0)
    with sched, _mk_router({"wa": "http://a", "wb": "http://b"}, post,
                           HEDGE_CFG) as router:
        fut = router.submit(5, 0)
        # the settle point fires on the WINNING sender thread, inline
        # in the done-callback — strictly after exactly-once resolution
        fut.add_done_callback(
            lambda f: schedules.sync_point("settled"))
        assert fut.result(timeout=15.0) == 10.0
        # let the losing leg land before reading stats
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with router._lock:
                legs = router._inflight_legs
            if len(calls) >= 2 and legs == 0:
                break
            time.sleep(0.01)
        stats = router.stats_dict()
    assert sched.finished(), (sched.trace, sched.passed)
    assert len(calls) == 2, "the hedge leg never dispatched"
    assert stats["hedge_fired"] == 1
    assert stats["hedge_won"] == (1 if hedge_wins else 0)
    assert stats["served"] == 1 and stats["failed"] == 0
    return fut.result()


def test_race_hedge_both_orders_bit_identical_exactly_once():
    assert _race_hedge(hedge_wins=True) == _race_hedge(hedge_wins=False)


# -- 4. race 2: remove_worker vs. in-flight dispatch -----------------------


REMOVE_CFG = FleetConfig(router_flush_deadline_ms=0.0,
                         health_poll_interval_s=60.0,
                         dispatch_timeout_s=10.0)


def _race_remove(remove_first: bool) -> float:
    def post(base_url, entries, ts, timeout_s, trace=None, slo=None,
             dg=None):
        return [{"pred": float(e) * 2.0} for e in entries]

    script = (["remove.done", "fleet.assign.handoff",
               "fleet.assign.handoff_done"]
              if remove_first else
              ["fleet.assign.handoff", "fleet.assign.handoff_done",
               "remove.done"])
    sched = ScriptedScheduler(script, timeout_s=15.0)
    with sched, _mk_router({"w1": "http://w1", "w2": "http://w2"},
                           post, REMOVE_CFG) as router:
        fut = router.submit(5, 0)
        if remove_first:
            # wait until the dispatcher has CHOSEN w1 (deterministic:
            # both idle, ties break on worker_id) and is parked at the
            # handoff sync point — the exact window the membership
            # re-check in _assign exists for — then retire w1
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if router.stats_dict()["dispatched_batches"] >= 1:
                    break
                time.sleep(0.005)
            router.remove_worker("w1")
        else:
            # block until the handoff to w1 completed, THEN retire it:
            # the flight is already in (or past) w1's sender queue
            schedules.sync_point("remove.done")
            router.remove_worker("w1")
        if remove_first:
            schedules.sync_point("remove.done")
        assert fut.result(timeout=15.0) == 10.0
        stats = router.stats_dict()
    assert sched.finished(), (sched.trace, sched.passed)
    assert stats["served"] == 1 and stats["failed"] == 0
    assert stats["worker_removed"] == 1
    if remove_first:
        # the re-check caught the retirement: the flight was re-chosen
        # onto w2, never swallowed by w1's exiting sender
        assert "w1" not in stats["workers"]
        assert stats["workers"]["w2"]["dispatches"] >= 1
    return fut.result()


def test_race_remove_worker_both_orders_bit_identical():
    assert (_race_remove(remove_first=True)
            == _race_remove(remove_first=False))


# -- 5. race 3: drain vs. queue close --------------------------------------


class _RecorderBus:
    """Just enough bus for MicrobatchQueue, with counter capture."""

    enabled = True

    def __init__(self):
        self.counters: list[tuple] = []
        self._lock = threading.Lock()

    def counter(self, name, value=1, **tags):
        with self._lock:
            self.counters.append((name, value))

    def count(self, name) -> int:
        with self._lock:
            return sum(v for n, v in self.counters if n == name)

    def gauge(self, *a, **k):
        pass

    def histogram(self, *a, **k):
        pass

    def trace_span(self, *a, **k):
        pass

    def finish_trace(self, *a, **k):
        pass

    def start_trace(self, *a, **k):
        return None


class _FakeEngine:
    """Engine-shaped stub: deterministic predictions, no jax — the
    queue's protocol is the subject, not the model."""

    def __init__(self):
        self._cfg = SimpleNamespace(serve=ServeConfig())
        self.bus = _RecorderBus()
        self.healthy = True
        self.unhealthy_reason = ""
        self.lens_local = False
        rung = SimpleNamespace(max_graphs=8, max_nodes=512,
                               max_edges=512)
        self.ladder = [rung]
        self.last_stage_tm: dict = {}

    def request_size(self, eid):
        return (4, 4)

    def predict_microbatch(self, entries, ts_buckets, max_rung=None,
                           mixtures=None):
        return [float(e) * 2.0 for e in entries]

    def record_queue_wait(self, dt, coalesced=0):
        pass


def _race_drain_close(drain_first: bool):
    eng = _FakeEngine()
    q = MicrobatchQueue(eng, flush_deadline_ms=10_000.0,
                        max_pending=64, request_deadline_ms=0.0,
                        dispatch_timeout_s=0.0, overlap_dispatch=False,
                        trace_roots=False)
    futs = [q.submit(i + 1, 0) for i in range(4)]
    script = (["go.drain", "drain.done", "go.close", "close.done"]
              if drain_first else
              ["go.close", "close.done", "go.drain", "drain.done"])
    sched = ScriptedScheduler(script, timeout_s=15.0)
    with sched:
        def do_drain():
            schedules.sync_point("go.drain")
            q.begin_drain()
            schedules.sync_point("drain.done")

        def do_close():
            schedules.sync_point("go.close")
            q.close()
            schedules.sync_point("close.done")

        ts = [threading.Thread(target=do_drain, name="race-drain"),
              threading.Thread(target=do_close, name="race-close")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=15.0)
        assert not any(t.is_alive() for t in ts), "drain/close wedged"
    assert sched.finished(), (sched.trace, sched.passed)
    # exactly-once, bit-identical: every admitted future resolves to
    # its own prediction regardless of the order the race ran in
    preds = [f.result(timeout=10.0) for f in futs]
    assert preds == [2.0, 4.0, 6.0, 8.0]
    # post-close admission is a typed refusal, never a lost future
    with pytest.raises(QueueClosed):
        q.submit(9, 0)
    return preds, eng.bus.count("serve.drain_begin")


def test_race_drain_close_both_orders_bit_identical():
    preds_a, drains_a = _race_drain_close(drain_first=True)
    preds_b, drains_b = _race_drain_close(drain_first=False)
    assert preds_a == preds_b
    # the drain marker fires exactly once when a drain was requested
    # before close finished the lifecycle; a post-close begin_drain is
    # a no-op flag write (nothing left to announce it)
    assert drains_a == 1 and drains_b == 0
