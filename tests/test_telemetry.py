"""Telemetry bus: schema round-trip, levels, no-op overhead bound, and
the train/serve instrumentation (ISSUE 2 acceptance: JSONL streams carry
the per-epoch host/device split, pad waste, cache counters, and the
per-request span breakdown)."""

import json
import os
import time

import numpy as np
import pytest

from pertgnn_tpu import telemetry
from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import Config, DataConfig, IngestConfig, TrainConfig
from pertgnn_tpu.telemetry import (MetricsWriter, SchemaError, TelemetryBus,
                                   iter_events, load_events, validate_event)
from pertgnn_tpu.telemetry.schema import SCHEMA_VERSION


@pytest.fixture()
def scratch_bus(tmp_path):
    """A real bus writing to a tmp JSONL, installed process-wide for the
    test (global-bus consumers like the packer see it) and torn down
    after."""
    writer = MetricsWriter(str(tmp_path / "tele"))
    bus = TelemetryBus(writer, level="trace")
    prev = telemetry.set_bus(bus)
    yield bus, writer.path
    telemetry.set_bus(prev)
    bus.close()


def _names(path):
    return [e["name"] for e in load_events(path)]


class TestSchema:
    def _base(self, **kw):
        ev = {"v": SCHEMA_VERSION, "t": 1.0, "tm": 2.0, "pid": 1,
              "pi": 0, "kind": "counter", "name": "x", "value": 1}
        ev.update(kw)
        return ev

    def test_v1_events_stay_readable(self):
        ev = self._base(v=1)
        del ev["tm"]  # v1 predates the monotonic stamp
        validate_event(ev)

    def test_v2_requires_monotonic_stamp(self):
        ev = self._base()
        del ev["tm"]
        with pytest.raises(SchemaError, match="tm"):
            validate_event(ev)

    def test_trace_fields_validate(self):
        ev = self._base(kind="span", trace_id="ab", span_id="1.2",
                        parent_span_id="1.1", tm0=1.5)
        del ev["value"]
        ev["dur_ms"] = 1.0
        validate_event(ev)
        # trace identity off a span event is a schema violation
        with pytest.raises(SchemaError, match="span events only"):
            validate_event(self._base(trace_id="ab"))
        # span ids without a trace id are unanchorable
        bad = self._base(kind="span", span_id="1.2")
        del bad["value"]
        bad["dur_ms"] = 1.0
        with pytest.raises(SchemaError, match="trace_id"):
            validate_event(bad)

    def test_valid_kinds(self):
        validate_event(self._base())
        validate_event(self._base(kind="gauge", value=0.5))
        validate_event(self._base(kind="histogram", value=2))
        ev = self._base(kind="span")
        del ev["value"]
        ev["dur_ms"] = 1.5
        validate_event(ev)
        ev = self._base(kind="meta")
        del ev["value"]
        ev["fields"] = {"a": 1}
        validate_event(ev)

    @pytest.mark.parametrize("mutation", [
        {"v": 999}, {"kind": "nope"}, {"name": ""}, {"t": None},
        {"pid": "1"}, {"value": "fast"}, {"value": True},
        {"tags": {"k": [1, 2]}}, {"tags": "notadict"},
    ])
    def test_invalid_events_raise(self, mutation):
        with pytest.raises(SchemaError):
            validate_event(self._base(**mutation))

    def test_span_needs_duration(self):
        ev = self._base(kind="span")
        del ev["value"]
        with pytest.raises(SchemaError):
            validate_event(ev)

    def test_crash_tail_skipped_but_corruption_raises(self):
        good = json.dumps(self._base())
        # a truncated FINAL line is the crash signature: tolerated
        assert len(list(iter_events([good, good[:17]]))) == 1
        # the same truncation mid-stream is corruption: strict raises
        with pytest.raises(SchemaError):
            list(iter_events([good[:17], good]))
        assert len(list(iter_events([good[:17], good], strict=False))) == 1

    def test_schema_invalid_final_line_is_not_a_crash_tail(self):
        """A complete-but-invalid final event (drifted writer, future
        schema version) must surface in strict mode — only TRUNCATED
        trailing lines get the crash-tail tolerance."""
        good = json.dumps(self._base())
        bad = json.dumps(self._base(v=999))
        with pytest.raises(SchemaError):
            list(iter_events([good, bad]))
        assert len(list(iter_events([good, bad], strict=False))) == 1


class TestWriterAndBus:
    def test_round_trip_all_kinds(self, scratch_bus):
        bus, path = scratch_bus
        bus.counter("c", 2, bucket=3)
        bus.gauge("g", 0.25, epoch=1)
        bus.histogram("h", 9.0)
        with bus.span("s", stage="pack"):
            pass
        bus.event("e", fields={"k": "v"})
        bus.flush()
        evs = load_events(path)  # validates every event
        assert [e["kind"] for e in evs] == [
            "meta", "counter", "gauge", "histogram", "span", "meta"]
        assert evs[0]["name"] == "run_start"
        assert evs[0]["fields"]["schema_version"] == SCHEMA_VERSION
        assert all(e["pid"] == os.getpid() for e in evs)
        assert evs[1]["tags"] == {"bucket": 3}
        assert evs[4]["dur_ms"] >= 0

    def test_level_filtering(self, tmp_path):
        writer = MetricsWriter(str(tmp_path / "lvl"))
        bus = TelemetryBus(writer, level="basic")
        bus.counter("kept", 1)
        bus.counter("dropped", 1, level=2)
        assert bus.span("dropped_span", level=2) is telemetry.NULL_SPAN
        with bus.span("kept_span"):
            pass
        bus.close()
        names = _names(writer.path)
        assert "kept" in names and "kept_span" in names
        assert "dropped" not in names and "dropped_span" not in names

    def test_wrap_decorator(self, scratch_bus):
        bus, path = scratch_bus

        @bus.wrap("timed_fn")
        def f(x):
            return x + 1

        assert f(1) == 2
        bus.flush()
        assert "timed_fn" in _names(path)

    def test_configure_and_shutdown(self, tmp_path):
        bus = telemetry.configure(str(tmp_path / "cfg"), "basic",
                                  jax_monitoring=False)
        try:
            assert telemetry.get_bus() is bus and bus.enabled
            with telemetry.span("via_module"):
                pass
        finally:
            telemetry.shutdown()
        assert not telemetry.get_bus().enabled
        assert "via_module" in _names(bus.path)

    def test_configure_off_is_noop(self, tmp_path):
        assert telemetry.configure("", "trace") is telemetry.NOOP_BUS
        assert telemetry.configure(str(tmp_path), "off") is telemetry.NOOP_BUS
        assert not os.listdir(tmp_path)

    def test_noop_overhead_bound(self):
        """The disabled bus must cost microseconds per call site — the
        strict <1% bound vs a real train step lives in
        benchmarks/telemetry_overhead.py; this is the CI-safe version."""
        bus = telemetry.NOOP_BUS
        n = 20_000
        t0 = time.perf_counter()
        for i in range(n):
            bus.counter("x", 1, step=i)
            with bus.span("y", level=2, step=i):
                pass
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 50e-6, f"noop bundle took {per_call * 1e6:.1f} us"


class TestJaxMonitoring:
    def test_compile_events_forwarded(self, scratch_bus):
        import jax
        import jax.numpy as jnp

        bus, path = scratch_bus
        uninstall = telemetry.install_jax_monitoring(bus)
        try:
            # a fresh closure + unusual shape forces a real compile
            jax.jit(lambda x: x * 3 + 1)(jnp.ones((7, 3)))
        finally:
            uninstall()
        n_before = len(load_events(path))
        jax.jit(lambda x: x * 5 + 2)(jnp.ones((11, 3)))
        bus.flush()
        evs = load_events(path)
        assert any(e["name"].startswith("jax") for e in evs)
        assert len(evs) == n_before, "uninstalled listener still wrote"


class TestRecorders:
    def test_latency_recorder_exact_below_cap(self):
        from pertgnn_tpu.utils.profiling import LatencyRecorder
        r = LatencyRecorder(max_samples=100)
        for v in [1, 2, 3, 4]:
            r.record_s(v / 1e3)
        s = r.summary_dict()
        assert s["count"] == 4
        assert s["min_ms"] == pytest.approx(1) and s["max_ms"] == \
            pytest.approx(4)
        assert s["mean_ms"] == pytest.approx(2.5)
        assert r.percentile_ms(50) == pytest.approx(2.5)

    def test_latency_recorder_reservoir_bounds_memory(self):
        from pertgnn_tpu.utils.profiling import LatencyRecorder
        r = LatencyRecorder(max_samples=64)
        for i in range(10_000):
            r.record_s(i / 1e3)
        assert len(r._ms) == 64
        s = r.summary_dict()
        # exact over the full stream even though only 64 samples remain
        assert s["count"] == 10_000
        assert s["min_ms"] == pytest.approx(0.0)
        assert s["max_ms"] == pytest.approx(9999.0)
        assert s["mean_ms"] == pytest.approx(np.mean(np.arange(10_000)))
        # the reservoir is a uniform sample: p50 lands near the true
        # median with generous slack
        assert 2000 < s["p50_ms"] < 8000

    def test_step_timer_matches_serving_schema(self):
        from pertgnn_tpu.utils.profiling import (SUMMARY_KEYS,
                                                 LatencyRecorder, StepTimer)
        t = StepTimer()
        for _ in range(5):
            with t:
                pass
        td, sd = t.summary_dict(), LatencyRecorder().summary_dict()
        assert set(td) == set(sd) | {"ema_ms"} == set(SUMMARY_KEYS) | \
            {"ema_ms"}
        assert td["count"] == 5 and td["ema_ms"] is not None
        assert td["min_ms"] <= td["p50_ms"] <= td["max_ms"]
        assert "5 steps" in t.summary()


@pytest.fixture(scope="module")
def tele_cfg():
    return Config(ingest=IngestConfig(min_traces_per_entry=10),
                  data=DataConfig(max_traces=200, batch_size=16),
                  train=TrainConfig(label_scale=1000.0, scan_chunk=4,
                                    epochs=1))


class TestTrainInstrumentation:
    def test_fit_emits_epoch_split_and_pad_waste(self, preprocessed,
                                                 tele_cfg, scratch_bus):
        from pertgnn_tpu.train.loop import fit

        bus, path = scratch_bus
        ds = build_dataset(preprocessed, tele_cfg)
        _, history = fit(ds, tele_cfg, epochs=1, bus=bus)
        bus.flush()
        evs = load_events(path)
        names = [e["name"] for e in evs]
        for want in ("train.epoch_host_s", "train.epoch_device_s",
                     "train.graphs", "train.donated_buffer_dispatches",
                     "pack.pad_waste", "train.eval"):
            assert want in names, f"missing {want} in {set(names)}"
        # the split is mirrored into the history rows
        assert history[0]["host_time_s"] >= 0
        assert history[0]["device_time_s"] > 0
        pw = next(e for e in evs if e["name"] == "pack.pad_waste")
        assert 0.0 <= pw["value"] < 1.0
        assert pw["tags"]["batches"] >= 1

    def test_injected_bus_without_global_captures_pack_events(
            self, preprocessed, tele_cfg, tmp_path):
        """fit(bus=...) with the process-wide bus left at the no-op:
        the injected bus must be scoped process-wide for the call so the
        global-bus call sites underneath (packer pad waste, checkpoint
        spans) land on it — and restored after."""
        from pertgnn_tpu.train.loop import fit

        assert not telemetry.get_bus().enabled
        writer = MetricsWriter(str(tmp_path / "inj"))
        bus = TelemetryBus(writer, level="trace")
        ds = build_dataset(preprocessed, tele_cfg)
        fit(ds, tele_cfg, epochs=1, bus=bus)
        bus.close()
        assert not telemetry.get_bus().enabled, "global bus not restored"
        names = _names(writer.path)
        assert "pack.pad_waste" in names
        assert "train.epoch_host_s" in names


class TestServeInstrumentation:
    @pytest.fixture(scope="class")
    def served_bus(self, preprocessed, tmp_path_factory):
        """A tiny warmed engine wired to a real bus (class-scoped: the
        warmup compile is the expensive part)."""
        from pertgnn_tpu.config import ServeConfig
        from pertgnn_tpu.serve.engine import InferenceEngine
        from pertgnn_tpu.train.loop import restore_target_state

        cfg = Config(ingest=IngestConfig(min_traces_per_entry=10),
                     data=DataConfig(max_traces=200, batch_size=16),
                     train=TrainConfig(label_scale=1000.0),
                     serve=ServeConfig(bucket_growth=4.0,
                                       max_graphs_per_batch=4))
        ds = build_dataset(preprocessed, cfg)
        _, state = restore_target_state(ds, cfg)
        writer = MetricsWriter(str(tmp_path_factory.mktemp("tele")))
        bus = TelemetryBus(writer, level="trace")
        engine = InferenceEngine.from_dataset(ds, cfg, state,
                                              bus=bus).warmup()
        yield ds, engine, bus, writer.path
        bus.close()

    def test_request_span_breakdown(self, served_bus):
        ds, engine, bus, path = served_bus
        s = ds.splits["test"]
        engine.predict_microbatch(s.entry_ids[:2], s.ts_buckets[:2])
        bus.flush()
        names = _names(path)
        for want in ("serve.warmup", "serve.compile", "serve.cache_hit",
                     "serve.pack", "serve.dispatch", "serve.compute",
                     "serve.pad_waste"):
            assert want in names, f"missing {want}"
        stats = engine.stats_dict()
        assert set(stats["stages"]) == {"queue", "pack", "dispatch",
                                        "compute"}
        for stage in ("pack", "dispatch", "compute"):
            assert stats["stages"][stage]["count"] >= 1

    def test_queue_wait_and_publish(self, served_bus):
        from pertgnn_tpu.serve.queue import MicrobatchQueue

        ds, engine, bus, path = served_bus
        s = ds.splits["test"]
        with MicrobatchQueue(engine, flush_deadline_ms=5) as q:
            futs = [q.submit(int(s.entry_ids[i]), int(s.ts_buckets[i]))
                    for i in range(3)]
            [f.result(timeout=30) for f in futs]
        stats = engine.publish_stats()
        bus.flush()
        assert stats["stages"]["queue"]["count"] >= 3
        evs = load_events(path)
        names = [e["name"] for e in evs]
        assert "serve.queue_wait_ms" in names
        assert "serve.request_total_ms" in names
        assert "serve.stats" in names
        # per-bucket pad waste lands at BASIC level via publish_stats
        bw = [e for e in evs if e["name"] == "serve.bucket_pad_waste"]
        assert bw and all(0 <= e["value"] < 1 for e in bw)
        assert all("bucket" in e["tags"] for e in bw)
