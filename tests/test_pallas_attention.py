"""Parity tests for the fused Pallas edge-attention kernel.

On the CPU test platform the kernel runs in interpreter mode (it
auto-detects the backend); the compiled path is exercised by bench runs on
the real chip. Oracle: the XLA segment-op formulation (`_reference`), which
is itself parity-tested against a dense numpy oracle in test_model.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pertgnn_tpu.ops.pallas_attention import _reference, edge_attention


def _case(rng, n, e, heads, dim, mask_frac=0.2, sort=False):
    q = jnp.asarray(rng.normal(size=(n, heads, dim)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(e, heads, dim)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(e, heads, dim)), jnp.float32)
    rcv = rng.integers(0, n, e)
    mask = rng.random(e) > mask_frac
    if sort:
        order = np.argsort(np.where(mask, rcv, n), kind="stable")
        rcv, mask = rcv[order], mask[order]
        k, v = k[order], v[order]
    return q, k, v, jnp.asarray(rcv, jnp.int32), jnp.asarray(mask)


@pytest.mark.parametrize("n,e,heads,dim", [
    (50, 200, 1, 32),    # typical
    (300, 700, 4, 16),   # multi-head, lane-unaligned head slices
    (5, 3, 2, 8),        # fewer edges than nodes; empty receivers
    (130, 1, 1, 8),      # single edge; block-boundary node count
    (260, 900, 1, 8),    # multiple node blocks
])
def test_kernel_matches_segment_path(n, e, heads, dim):
    rng = np.random.default_rng(n + e)
    q, k, v, rcv, mask = _case(rng, n, e, heads, dim)
    out = edge_attention(q, k, v, rcv, mask, n)
    ref = _reference(q, k, v, rcv, mask, n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_assume_sorted_path():
    rng = np.random.default_rng(0)
    q, k, v, rcv, mask = _case(rng, 100, 400, 1, 16, sort=True)
    out = edge_attention(q, k, v, rcv, mask, 100, assume_sorted=True)
    ref = _reference(q, k, v, rcv, mask, 100)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_assume_sorted_guard_on_unsorted_input():
    """assume_sorted=True on a batch violating the invariant must fall back
    to the (correct) segment path, never silently drop edges."""
    rng = np.random.default_rng(3)
    q, k, v, rcv, mask = _case(rng, 100, 400, 1, 16, sort=False)
    assert not (np.diff(np.where(np.asarray(mask), np.asarray(rcv), 100))
                >= 0).all()
    out = edge_attention(q, k, v, rcv, mask, 100, assume_sorted=True)
    ref = _reference(q, k, v, rcv, mask, 100)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_all_edges_masked_gives_zeros():
    rng = np.random.default_rng(1)
    q, k, v, rcv, _ = _case(rng, 40, 60, 1, 8)
    out = edge_attention(q, k, v, rcv, jnp.zeros(60, bool), 40)
    assert np.abs(np.asarray(out)).max() == 0.0


def test_gradients_match_segment_path():
    rng = np.random.default_rng(2)
    q, k, v, rcv, mask = _case(rng, 60, 150, 2, 8)

    def loss_pal(q, k, v):
        return (edge_attention(q, k, v, rcv, mask, 60) ** 2).sum()

    def loss_ref(q, k, v):
        return (_reference(q, k, v, rcv, mask, 60) ** 2).sum()

    g1 = jax.grad(loss_pal, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_stack_batches_preserves_sorted_invariant():
    """Data-parallel stacking must re-establish the receiver-sorted edge
    order the Pallas kernel's block-skipping relies on (pack.py invariant):
    naive concatenation leaves each shard's pad tail between sorted runs."""
    from pertgnn_tpu.parallel.data_parallel import stack_batches
    from tests.test_model import _tiny_batch

    shards = [_tiny_batch(seed=s, pad_nodes=7, pad_edges=5)
              for s in (0, 1)]
    glob = stack_batches(shards)
    n_tot = glob.x.shape[0]
    key = np.where(glob.edge_mask, glob.receivers, n_tot)
    assert (np.diff(key) >= 0).all()
    # and real-edge multiset is preserved across the re-sort
    want = sorted(
        [(int(r), int(s)) for b, off in zip(shards, (0, shards[0].x.shape[0]))
         for r, s, m in zip(b.receivers + off, b.senders + off, b.edge_mask)
         if m])
    got = sorted([(int(r), int(s)) for r, s, m in
                  zip(glob.receivers, glob.senders, glob.edge_mask) if m])
    assert want == got


def test_model_forward_with_pallas_flag():
    """The full model runs (and pads are invisible) with the kernel on.
    PackedBatch edges are receiver-sorted by pack.flush, which the layer's
    assume_sorted relies on."""
    from pertgnn_tpu.config import ModelConfig
    from pertgnn_tpu.models.pert_model import make_model
    from tests.test_model import _tiny_batch

    b = jax.tree.map(jnp.asarray, _tiny_batch())
    outs = {}
    for flag in (False, True):
        cfg = ModelConfig(hidden_channels=16, num_layers=2,
                          use_pallas_attention=flag)
        model = make_model(cfg, num_ms=5, num_entries=4, num_interfaces=4,
                           num_rpctypes=3)
        vars_ = model.init(jax.random.PRNGKey(0), b, training=False)
        outs[flag] = model.apply(vars_, b, training=False)
    np.testing.assert_allclose(np.asarray(outs[False][0]),
                               np.asarray(outs[True][0]),
                               rtol=1e-4, atol=1e-5)


def test_gradients_match_on_sorted_cond_path():
    """The path the model actually differentiates: assume_sorted=True with
    the runtime guard taking the fused-kernel branch (fwd + fused bwd)."""
    rng = np.random.default_rng(9)
    q, k, v, rcv, mask = _case(rng, 80, 320, 2, 16, sort=True)
    assert (np.diff(np.where(np.asarray(mask), np.asarray(rcv), 80))
            >= 0).all()

    def loss_pal(q, k, v):
        return (edge_attention(q, k, v, rcv, mask, 80,
                               assume_sorted=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (_reference(q, k, v, rcv, mask, 80) ** 2).sum()

    g1 = jax.jit(jax.grad(loss_pal, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
