"""Parity tests for the fused Pallas edge-attention kernel.

On the CPU test platform the kernel runs in interpreter mode (it
auto-detects the backend); the compiled path is exercised by bench runs on
the real chip. Oracle: the XLA segment-op formulation (`_reference`), which
is itself parity-tested against a dense numpy oracle in test_model.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pertgnn_tpu.ops.pallas_attention import (_reference, edge_attention,
                                              fused_epilogue)


def _case(rng, n, e, heads, dim, mask_frac=0.2, sort=False):
    q = jnp.asarray(rng.normal(size=(n, heads, dim)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(e, heads, dim)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(e, heads, dim)), jnp.float32)
    rcv = rng.integers(0, n, e)
    mask = rng.random(e) > mask_frac
    if sort:
        order = np.argsort(np.where(mask, rcv, n), kind="stable")
        rcv, mask = rcv[order], mask[order]
        k, v = k[order], v[order]
    return q, k, v, jnp.asarray(rcv, jnp.int32), jnp.asarray(mask)


@pytest.mark.parametrize("n,e,heads,dim", [
    (50, 200, 1, 32),    # typical
    (300, 700, 4, 16),   # multi-head, lane-unaligned head slices
    (5, 3, 2, 8),        # fewer edges than nodes; empty receivers
    (130, 1, 1, 8),      # single edge; block-boundary node count
    (260, 900, 1, 8),    # multiple node blocks
])
def test_kernel_matches_segment_path(n, e, heads, dim):
    rng = np.random.default_rng(n + e)
    q, k, v, rcv, mask = _case(rng, n, e, heads, dim)
    out = edge_attention(q, k, v, rcv, mask, n)
    ref = _reference(q, k, v, rcv, mask, n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_assume_sorted_path():
    rng = np.random.default_rng(0)
    q, k, v, rcv, mask = _case(rng, 100, 400, 1, 16, sort=True)
    out = edge_attention(q, k, v, rcv, mask, 100, assume_sorted=True)
    ref = _reference(q, k, v, rcv, mask, 100)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_assume_sorted_guard_on_unsorted_input():
    """assume_sorted=True on a batch violating the invariant must fall back
    to the (correct) segment path, never silently drop edges."""
    rng = np.random.default_rng(3)
    q, k, v, rcv, mask = _case(rng, 100, 400, 1, 16, sort=False)
    assert not (np.diff(np.where(np.asarray(mask), np.asarray(rcv), 100))
                >= 0).all()
    out = edge_attention(q, k, v, rcv, mask, 100, assume_sorted=True)
    ref = _reference(q, k, v, rcv, mask, 100)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_all_edges_masked_gives_zeros():
    rng = np.random.default_rng(1)
    q, k, v, rcv, _ = _case(rng, 40, 60, 1, 8)
    out = edge_attention(q, k, v, rcv, jnp.zeros(60, bool), 40)
    assert np.abs(np.asarray(out)).max() == 0.0


def test_gradients_match_segment_path():
    rng = np.random.default_rng(2)
    q, k, v, rcv, mask = _case(rng, 60, 150, 2, 8)

    def loss_pal(q, k, v):
        return (edge_attention(q, k, v, rcv, mask, 60) ** 2).sum()

    def loss_ref(q, k, v):
        return (_reference(q, k, v, rcv, mask, 60) ** 2).sum()

    g1 = jax.grad(loss_pal, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_stack_batches_preserves_sorted_invariant():
    """Data-parallel stacking must re-establish the receiver-sorted edge
    order the Pallas kernel's block-skipping relies on (pack.py invariant):
    naive concatenation leaves each shard's pad tail between sorted runs."""
    from pertgnn_tpu.parallel.data_parallel import stack_batches
    from tests.test_model import _tiny_batch

    shards = [_tiny_batch(seed=s, pad_nodes=7, pad_edges=5)
              for s in (0, 1)]
    glob = stack_batches(shards)
    n_tot = glob.x.shape[0]
    key = np.where(glob.edge_mask, glob.receivers, n_tot)
    assert (np.diff(key) >= 0).all()
    # and real-edge multiset is preserved across the re-sort
    want = sorted(
        [(int(r), int(s)) for b, off in zip(shards, (0, shards[0].x.shape[0]))
         for r, s, m in zip(b.receivers + off, b.senders + off, b.edge_mask)
         if m])
    got = sorted([(int(r), int(s)) for r, s, m in
                  zip(glob.receivers, glob.senders, glob.edge_mask) if m])
    assert want == got


def _epilogue_case(rng, n, f_in, hd, mask_frac=0.3):
    attn = jnp.asarray(rng.normal(size=(n, hd)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(n, f_in)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(f_in, hd)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(hd,)), jnp.float32)
    node_mask = jnp.asarray(rng.random(n) > mask_frac)
    return attn, x, w, b, node_mask


def _epilogue_ref(attn, x, w, b, node_mask):
    y = attn + x @ w + b[None, :]
    m = node_mask.astype(jnp.float32)[:, None]
    ym = y * m
    return y, jnp.stack([ym.sum(0), (ym * y).sum(0)])


class TestFusedEpilogue:
    """fused_epilogue = skip GEMM + residual + masked BN-stat partials in
    one Pallas pass (interpret mode on CPU). Oracle: the plain-XLA
    formulation the unfused layer path computes."""

    @pytest.mark.parametrize("n,f_in,hd", [
        (37, 12, 16),    # sub-block node count
        (128, 9, 32),    # exactly one node block
        (300, 33, 8),    # multi-block, lane-unaligned feature widths
    ])
    def test_forward_matches_unfused(self, n, f_in, hd):
        rng = np.random.default_rng(n)
        attn, x, w, b, node_mask = _epilogue_case(rng, n, f_in, hd)
        y, stats = fused_epilogue(attn, x, w, b, node_mask)
        y_ref, stats_ref = _epilogue_ref(attn, x, w, b, node_mask)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(stats), np.asarray(stats_ref),
                                   rtol=1e-4, atol=1e-3)

    def test_all_nodes_masked_zero_stats(self):
        """Empty mask: y is still computed for every row (pad rows are
        dropped later by the caller), but the stat partials are zero."""
        rng = np.random.default_rng(5)
        attn, x, w, b, _ = _epilogue_case(rng, 50, 8, 16)
        y, stats = fused_epilogue(attn, x, w, b, jnp.zeros(50, bool))
        y_ref, _ = _epilogue_ref(attn, x, w, b, jnp.zeros(50, bool))
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-5)
        assert np.abs(np.asarray(stats)).max() == 0.0

    def test_gradients_match_unfused(self):
        """Full cotangent surface: a loss that consumes BOTH outputs (y
        and the stat partials) so the custom bwd's stats term is
        exercised, wrt every differentiable operand."""
        rng = np.random.default_rng(6)
        attn, x, w, b, node_mask = _epilogue_case(rng, 90, 10, 16)

        def loss_fused(attn, x, w, b):
            y, stats = fused_epilogue(attn, x, w, b, node_mask)
            return (y ** 2).sum() + (stats * 0.1).sum()

        def loss_ref(attn, x, w, b):
            y, stats = _epilogue_ref(attn, x, w, b, node_mask)
            return (y ** 2).sum() + (stats * 0.1).sum()

        g1 = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(attn, x, w, b)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(attn, x, w, b)
        for a, r in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=1e-4, atol=1e-3)

    def test_jit_path(self):
        rng = np.random.default_rng(7)
        attn, x, w, b, node_mask = _epilogue_case(rng, 70, 8, 8)
        y, stats = jax.jit(fused_epilogue)(attn, x, w, b, node_mask)
        y_ref, stats_ref = _epilogue_ref(attn, x, w, b, node_mask)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(stats),
                                   np.asarray(stats_ref),
                                   rtol=1e-4, atol=1e-3)


class TestBlockedDense:
    """ops/blocked_dense: the segment ops as masked dense matmuls.
    Oracle: the segment reference — same contract as the Pallas kernel
    (tests above), asserted over the same corner cases."""

    @pytest.mark.parametrize("n,e,heads,dim", [
        (50, 200, 1, 32),
        (300, 700, 4, 16),
        (5, 3, 2, 8),      # fewer edges than nodes; empty receivers
        (130, 1, 1, 8),
    ])
    def test_matches_segment_path(self, n, e, heads, dim):
        from pertgnn_tpu.ops.blocked_dense import blocked_dense_edge_attention

        rng = np.random.default_rng(n + e + 1)
        q, k, v, rcv, mask = _case(rng, n, e, heads, dim)
        out = blocked_dense_edge_attention(q, k, v, rcv, mask, n)
        ref = _reference(q, k, v, rcv, mask, n)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_all_edges_masked_gives_zeros(self):
        from pertgnn_tpu.ops.blocked_dense import blocked_dense_edge_attention

        rng = np.random.default_rng(11)
        q, k, v, rcv, _ = _case(rng, 40, 60, 1, 8)
        out = blocked_dense_edge_attention(q, k, v, rcv,
                                           jnp.zeros(60, bool), 40)
        assert np.abs(np.asarray(out)).max() == 0.0

    def test_gradients_match_segment_path(self):
        from pertgnn_tpu.ops.blocked_dense import blocked_dense_edge_attention

        rng = np.random.default_rng(12)
        q, k, v, rcv, mask = _case(rng, 60, 150, 2, 8)

        def loss_bd(q, k, v):
            return (blocked_dense_edge_attention(q, k, v, rcv, mask,
                                                 60) ** 2).sum()

        def loss_ref(q, k, v):
            return (_reference(q, k, v, rcv, mask, 60) ** 2).sum()

        g1 = jax.jit(jax.grad(loss_bd, argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_admissibility_gate(self):
        """fits()/dense_cells: the max_cells guard the layer consults
        before materializing the quadratic incidence mask."""
        from pertgnn_tpu.ops.blocked_dense import dense_cells, fits

        assert dense_cells(100, 500) == 128 * 512
        assert dense_cells(1, 1, block_n=64, block_e=64) == 64 * 64
        assert fits(100, 500, max_cells=128 * 512)
        assert not fits(100, 513, max_cells=128 * 512)


def test_model_forward_with_pallas_flag():
    """The full model runs (and pads are invisible) with the kernel on.
    PackedBatch edges are receiver-sorted by pack.flush, which the layer's
    assume_sorted relies on."""
    from pertgnn_tpu.config import ModelConfig
    from pertgnn_tpu.models.pert_model import make_model
    from tests.test_model import _tiny_batch

    b = jax.tree.map(jnp.asarray, _tiny_batch())
    outs = {}
    for flag in (False, True):
        cfg = ModelConfig(hidden_channels=16, num_layers=2,
                          use_pallas_attention=flag)
        model = make_model(cfg, num_ms=5, num_entries=4, num_interfaces=4,
                           num_rpctypes=3)
        vars_ = model.init(jax.random.PRNGKey(0), b, training=False)
        outs[flag] = model.apply(vars_, b, training=False)
    np.testing.assert_allclose(np.asarray(outs[False][0]),
                               np.asarray(outs[True][0]),
                               rtol=1e-4, atol=1e-5)


def test_gradients_match_on_sorted_cond_path():
    """The path the model actually differentiates: assume_sorted=True with
    the runtime guard taking the fused-kernel branch (fwd + fused bwd)."""
    rng = np.random.default_rng(9)
    q, k, v, rcv, mask = _case(rng, 80, 320, 2, 16, sort=True)
    assert (np.diff(np.where(np.asarray(mask), np.asarray(rcv), 80))
            >= 0).all()

    def loss_pal(q, k, v):
        return (edge_attention(q, k, v, rcv, mask, 80,
                               assume_sorted=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (_reference(q, k, v, rcv, mask, 80) ** 2).sum()

    g1 = jax.jit(jax.grad(loss_pal, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
