"""Test harness: force an 8-fake-device CPU platform BEFORE jax import.

Multi-chip sharding logic is tested on a virtual CPU mesh
(SURVEY.md §4 "Distributed"); the real-TPU path is exercised by bench.py and
the driver's dryrun.
"""

import faulthandler
import os
import signal

# Tier-1 deadlock watchdog (ISSUE-14 satellite): the tier-1 command is
# `timeout -k 10 870 ... pytest ...` — at the budget, `timeout` sends
# SIGTERM, then SIGKILL 10 s later. Register faulthandler on SIGTERM so
# that moment dumps EVERY thread's stack to stderr: a lock-order
# regression (or any wedged thread the graftsync proofs missed)
# produces a readable report naming the threads (all named since this
# PR — thread-lifecycle pass) and the frames they are blocked in,
# instead of an opaque 870 s hard kill. faulthandler's handler is
# C-level and fires even when every Python thread is deadlocked (a
# Python signal handler would wait for the main thread's bytecode, i.e.
# forever). The process no longer dies on SIGTERM itself — `timeout
# -k`'s SIGKILL (or any supervisor's) remains the terminator, 10 s
# after the dump.
if hasattr(signal, "SIGTERM"):
    faulthandler.register(signal.SIGTERM, all_threads=True, chain=False)

# Override unconditionally: the live session presets JAX_PLATFORMS=axon (the
# one-chip TPU tunnel) and the axon plugin wins over the env var — the config
# update below is what actually forces CPU.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

# Belt and braces: drop the relay plugin's backend factory too — it
# re-sets jax_platforms at interpreter start and its get_backend hook
# has initialized the tunnel backend under a cpu config (round 5),
# which blocks forever when the relay is half-open.
from pertgnn_tpu.cli.common import drop_relay_backend_factory

drop_relay_backend_factory()

import numpy as np
import pytest

from pertgnn_tpu.config import Config, IngestConfig, DataConfig
from pertgnn_tpu.ingest import synthetic


@pytest.fixture(autouse=True)
def _isolate_pkg_logging():
    """setup_logging() (run by CLI tests) sets propagate=False and adds a
    handler on the package logger GLOBALLY — which silently breaks any
    later caplog-based test (caplog listens on root). Snapshot + restore
    around every test so logging state cannot leak across tests."""
    import logging

    pkg = logging.getLogger("pertgnn_tpu")
    prev = (pkg.propagate, list(pkg.handlers), pkg.level)
    yield
    pkg.propagate, pkg.level = prev[0], prev[2]
    pkg.handlers[:] = prev[1]


@pytest.fixture(scope="session")
def synth():
    """A small synthetic dataset shared across the session."""
    return synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=30, num_entries=3, patterns_per_entry=3,
        traces_per_entry=40, seed=7))


@pytest.fixture(scope="session")
def small_config():
    return Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=200, batch_size=16),
    )


@pytest.fixture(scope="session")
def preprocessed(synth, small_config):
    from pertgnn_tpu.ingest.preprocess import preprocess
    return preprocess(synth.spans, synth.resources, small_config.ingest)
