"""Property tests for the bounded double-buffered prefetch
(batching/prefetch.py).

THE law: ``prefetch_iter(items, fn, depth)`` is observationally
identical to the eager ``(fn(x) for x in items)`` — same values, same
order, bit-identical arrays — for every depth, every chunk-shape
sequence, an upstream that raises mid-stream, and a consumer that
closes early. The staged-epoch fallback in train/loop.py swaps the
eager loop for this iterator purely on that law; these tests are what
make the swap safe.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from pertgnn_tpu import telemetry
from pertgnn_tpu.batching.prefetch import prefetch_iter

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st
    _HAVE_HYPOTHESIS = True
except ImportError:  # deterministic fallback exercises fixed cases
    _HAVE_HYPOTHESIS = False

# Property tests run hypothesis-driven when the dev extra is installed
# (pip install -e .[dev]); without it the SAME laws are pinned over a
# fixed parameter grid so the invariants never go untested.
_needs_hypothesis = pytest.mark.skipif(
    not _HAVE_HYPOTHESIS, reason="property tests need the hypothesis dev "
                                 "extra (pip install -e .[dev]); grid "
                                 "twins below still cover the laws")

if _HAVE_HYPOTHESIS:
    # random "chunk" pytrees: dicts of arrays with hypothesis-drawn
    # shapes/dtypes — the shape family the staged fallback transfers
    _dtype = st.sampled_from([np.int32, np.int64, np.float32, np.bool_])

    @st.composite
    def _chunk(draw):
        rng = np.random.default_rng(draw(st.integers(0, 2**31 - 1)))
        out = {}
        for i in range(draw(st.integers(1, 4))):
            shape = tuple(draw(st.lists(st.integers(0, 5), min_size=1,
                                        max_size=3)))
            a = rng.integers(-100, 100, size=shape)
            out[f"f{i}"] = a.astype(draw(_dtype))
        return out


def _grid_chunks(seed: int, n: int) -> list[dict]:
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        out.append({
            "a": rng.integers(-100, 100,
                              size=tuple(rng.integers(0, 5, size=2))
                              ).astype(np.int32),
            "b": rng.standard_normal(int(rng.integers(0, 6))
                                     ).astype(np.float32),
        })
    return out


def _trees_equal(a, b) -> bool:
    if sorted(a) != sorted(b):
        return False
    return all(np.array_equal(a[k], b[k]) and a[k].dtype == b[k].dtype
               for k in a)


def _check_bit_identical(chunks, depth) -> None:
    def fn(c):
        return {k: v + 1 if v.dtype != np.bool_ else ~v
                for k, v in c.items()}

    eager = [fn(c) for c in chunks]
    got = list(prefetch_iter(iter(chunks), fn, depth=depth))
    assert len(got) == len(eager)
    for g, e in zip(got, eager):
        assert _trees_equal(g, e)


if _HAVE_HYPOTHESIS:
    @_needs_hypothesis
    @settings(max_examples=40, deadline=None)
    @given(chunks=st.lists(_chunk(), max_size=12),
           depth=st.integers(0, 4))
    def test_prefetch_bit_identical_to_eager(chunks, depth):
        """Random chunk shapes, any depth (0 = the eager oracle
        itself): identical output sequence, bit for bit."""
        _check_bit_identical(chunks, depth)


@pytest.mark.parametrize("depth", [0, 1, 2, 4])
@pytest.mark.parametrize("n", [0, 1, 7, 12])
def test_prefetch_bit_identical_grid(n, depth):
    """Deterministic grid twin of the hypothesis property (always
    runs, dev extra or not)."""
    _check_bit_identical(_grid_chunks(n * 31 + depth, n), depth)


def _check_raising_upstream(n_before, depth) -> None:
    class Boom(RuntimeError):
        pass

    def gen():
        for i in range(n_before):
            yield i
        raise Boom("upstream died")

    got = []
    with pytest.raises(Boom, match="upstream died"):
        for v in prefetch_iter(gen(), lambda x: x * 10, depth=depth):
            got.append(v)
    assert got == [i * 10 for i in range(n_before)]


if _HAVE_HYPOTHESIS:
    @_needs_hypothesis
    @settings(max_examples=30, deadline=None)
    @given(n_before=st.integers(0, 6), depth=st.integers(1, 4))
    def test_raising_upstream_propagates_after_prefix(n_before, depth):
        """An upstream exception reaches the CONSUMER, and only after
        every item produced before it was yielded — a poisoned epoch
        tail must never silently truncate the stream."""
        _check_raising_upstream(n_before, depth)


@pytest.mark.parametrize("depth", [1, 2, 4])
@pytest.mark.parametrize("n_before", [0, 1, 5])
def test_raising_upstream_grid(n_before, depth):
    _check_raising_upstream(n_before, depth)


def _check_early_close(take, depth) -> None:
    consumed = []

    def gen():
        for i in range(1000):
            consumed.append(i)
            yield i

    before = threading.active_count()
    it = prefetch_iter(gen(), None, depth=depth)
    got = [next(it) for _ in range(take)]
    it.close()
    assert got == list(range(take))
    # the producer may run ahead by the queue depth + one in-hand item
    # + one blocked-in-put item
    assert len(consumed) <= take + depth + 2
    # the producer thread is joined by close(), not leaked
    deadline = time.monotonic() + 5
    while (threading.active_count() > before
           and time.monotonic() < deadline):
        time.sleep(0.01)
    assert threading.active_count() <= before


if _HAVE_HYPOTHESIS:
    @_needs_hypothesis
    @settings(max_examples=30, deadline=None)
    @given(take=st.integers(0, 5), depth=st.integers(1, 4))
    def test_early_close_stops_producer_and_bounds_consumption(take,
                                                               depth):
        """Closing the consumer early (break / interrupt) joins the
        producer thread and consumes at most take + depth + buffered
        items upstream — no leak, no runaway epoch pack."""
        _check_early_close(take, depth)


@pytest.mark.parametrize("depth", [1, 3])
@pytest.mark.parametrize("take", [0, 2, 5])
def test_early_close_grid(take, depth):
    _check_early_close(take, depth)


class _GaugeBus(telemetry.NoopBus):
    enabled = True

    def __init__(self):
        self.gauges: dict[str, float] = {}

    def gauge(self, name, value, *, level=1, **tags):
        self.gauges[name] = value


def test_starvation_gauges_cover_the_wall():
    """device_starved (consumer waits) + host_starved (producer waits)
    are emitted on exhaustion and cannot exceed the iterator wall —
    the two sides are never blocked simultaneously."""
    bus = _GaugeBus()

    def slow_fn(x):
        time.sleep(0.003)
        return x

    out = list(prefetch_iter(iter(range(20)), slow_fn, depth=2, bus=bus,
                             source="test"))
    assert out == list(range(20))
    for name in ("prefetch.device_starved_s", "prefetch.host_starved_s",
                 "prefetch.wall_s"):
        assert name in bus.gauges, bus.gauges
    wall = bus.gauges["prefetch.wall_s"]
    total_starved = (bus.gauges["prefetch.device_starved_s"]
                     + bus.gauges["prefetch.host_starved_s"])
    # generous slack for scheduler noise: the law is "blocked time on
    # either side is bounded by the wall", not an exact decomposition
    assert total_starved <= wall * 1.5 + 0.05
    # a slow producer must show up as consumer starvation
    assert bus.gauges["prefetch.device_starved_s"] > 0


def test_depth_zero_is_synchronous_no_thread():
    before = threading.active_count()
    out = list(prefetch_iter(iter(range(5)), lambda x: -x, depth=0))
    assert out == [0, -1, -2, -3, -4]
    assert threading.active_count() == before
