"""Non-circular parity: the ACTUAL reference preprocessing vs this repo.

Runs /root/reference/preprocess.py verbatim (subprocess, pandas-3 dtype
shim only — see benchmarks/parity/reference_crosscheck.py) on synthetic
raw CSVs and compares its artifacts against our L0-L2 + graph builders.
This is the one test whose oracle is NOT written by this repo's author
(VERDICT r3 "What's missing" #1).
"""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REFERENCE = os.environ.get("PERTGNN_REFERENCE_DIR", "/root/reference")

_needs_reference = pytest.mark.skipif(
    not os.path.isfile(os.path.join(_REFERENCE, "preprocess.py")),
    reason="reference checkout not available")


def _run_crosscheck(tmp_path, seed=None) -> dict:
    """Run the harness and return its verdict after the shared assertions
    every invocation must satisfy (pass, enough checks, several runtime
    patterns). --traces 110 keeps a margin above the >100 entry-occurrence
    filter even after the 0.6-coverage filter drops some traces."""
    cmd = [sys.executable,
           os.path.join(_REPO, "benchmarks", "parity",
                        "reference_crosscheck.py"),
           "--traces", "110", "--sandbox", str(tmp_path / "sandbox")]
    if seed is not None:
        cmd += ["--seed", str(seed)]
    out = subprocess.run(cmd, capture_output=True, text=True, timeout=1500,
                         env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, (out.stdout[-3000:], out.stderr[-2000:])
    verdict = json.loads(out.stdout)
    assert verdict["pass"], verdict
    # every individual check must have actually run
    assert len(verdict["checks"]) >= 20
    assert verdict["runtimes"] > 1
    return verdict


@_needs_reference
def test_reference_preprocess_crosscheck(tmp_path):
    _run_crosscheck(tmp_path)


@pytest.mark.skipif(
    not os.environ.get("RUN_REF_SWEEP"),
    reason="opt-in (RUN_REF_SWEEP=1): randomized multi-seed cross-check "
           "against the reference's own preprocess — minutes per seed")
@_needs_reference
@pytest.mark.parametrize("seed", [101, 202, 303, 404])
def test_reference_preprocess_crosscheck_random_seeds(seed, tmp_path):
    """The default cross-check pins ONE synthetic corpus (seed 7); this
    sweep resamples the whole corpus (topologies, event timings, resource
    gaps) per seed, so each run is a fresh randomized comparison against
    the reference's actual executing code rather than a golden replay."""
    verdict = _run_crosscheck(tmp_path, seed=seed)
    assert verdict["seed"] == seed


def test_sandbox_seed_actually_changes_corpus(tmp_path):
    """Guards the sweep's premise: --seed must reach corpus generation.
    The verdict echoing args.seed can't detect a dropped pass-through
    (the sweep would silently re-check one golden corpus), so compare
    corpus fingerprints for two seeds directly."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "refxcheck", os.path.join(_REPO, "benchmarks", "parity",
                                  "reference_crosscheck.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    prints = {}
    for seed in (7, 8):
        root = str(tmp_path / f"s{seed}")
        os.makedirs(root)
        mod.make_sandbox(root, traces_per_entry=20, seed=seed)
        prints[seed] = mod.fingerprint_corpus(root)
    assert prints[7] != prints[8]
    # and the fingerprint itself is deterministic for a fixed seed
    root = str(tmp_path / "s7b")
    os.makedirs(root)
    mod.make_sandbox(root, traces_per_entry=20, seed=7)
    assert mod.fingerprint_corpus(root) == prints[7]
