"""Non-circular parity: the ACTUAL reference preprocessing vs this repo.

Runs /root/reference/preprocess.py verbatim (subprocess, pandas-3 dtype
shim only — see benchmarks/parity/reference_crosscheck.py) on synthetic
raw CSVs and compares its artifacts against our L0-L2 + graph builders.
This is the one test whose oracle is NOT written by this repo's author
(VERDICT r3 "What's missing" #1).
"""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REFERENCE = os.environ.get("PERTGNN_REFERENCE_DIR", "/root/reference")


@pytest.mark.skipif(
    not os.path.isfile(os.path.join(_REFERENCE, "preprocess.py")),
    reason="reference checkout not available")
def test_reference_preprocess_crosscheck(tmp_path):
    out = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "benchmarks", "parity",
                      "reference_crosscheck.py"),
         "--traces", "110", "--sandbox", str(tmp_path / "sandbox")],
        capture_output=True, text=True, timeout=1500,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, (out.stdout[-3000:], out.stderr[-2000:])
    verdict = json.loads(out.stdout)
    assert verdict["pass"], verdict
    # every individual check must have actually run
    assert len(verdict["checks"]) >= 20
    assert verdict["runtimes"] > 1
