"""Giant-corpus scale-out (pertgnn_tpu/parallel/scale.py, ISSUE 18).

The load-bearing guarantees:

- shard-to-host assignment is a pure function of the shard SET —
  permutation-invariant in the caller's order (hypothesis-pinned), so
  every host derives it without coordination, and disagreeing
  fingerprints REFUSE (HostAssignmentMismatch) before any statistics;
- the collective sharded merge is BIT-IDENTICAL to the single-host
  ``merge_shards`` oracle for any delta order and any host count, and
  refuses exactly where the oracle refuses (same guard code);
- SAR bucket accumulation: grad(remat scan) == grad(monolithic scan)
  BITWISE (tolerance 0, f32) at any capacity — the bit-stable
  checkpoint policy plus sum-then-divide-once arithmetic;
- the bucket CAPACITY is the only compiled dimension (live-count
  changes reuse one program; overflow refuses loudly), the remat step's
  compiled temp footprint is strictly below the monolithic twin's, and
  the per-bucket ``device.mem.peak_bytes`` gauges ride the bucket tag.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                ModelConfig, ScaleConfig, TrainConfig)
from pertgnn_tpu.ingest import synthetic
from pertgnn_tpu.ingest.assemble import assemble
from pertgnn_tpu.ingest.preprocess import preprocess
from pertgnn_tpu.models.pert_model import make_model
from pertgnn_tpu.parallel import scale
from pertgnn_tpu.parallel.mesh import make_mesh
from pertgnn_tpu.stream import (StreamRebuildRequired, base_shard,
                                ingest_delta, merge_shards,
                                shard_frames_by_window)
from pertgnn_tpu.stream.merge import canonical_key
from pertgnn_tpu.train.loop import create_train_state, fit, make_tx

SPAN_MS = 6 * 60 * 1000
BOUNDS = [SPAN_MS // 4, SPAN_MS // 2, 3 * SPAN_MS // 4]


class Capture:
    def __init__(self):
        self.counters, self.gauges, self.hists = [], [], []

    def counter(self, name, value=1, **tags):
        self.counters.append((name, value, tags))

    def gauge(self, name, value, **tags):
        self.gauges.append((name, value, tags))

    def histogram(self, name, value, **tags):
        self.hists.append((name, value, tags))


def _cfg(**kw) -> Config:
    base = dict(ingest=IngestConfig(min_traces_per_entry=5),
                data=DataConfig(max_traces=200, batch_size=4),
                model=ModelConfig(hidden_channels=16, num_layers=2),
                train=TrainConfig(label_scale=1000.0, scan_chunk=1,
                                  device_materialize=False, epochs=2),
                graph_type="pert")
    base.update(kw)
    return Config(**base)


@pytest.fixture(scope="module")
def corpus():
    """(cfg, base, deltas, oracle_ds, oracle_info): one synthetic corpus
    sliced into base + 3 windows, plus the single-host merge oracle."""
    cfg = _cfg()
    synth = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=12, num_entries=2, patterns_per_entry=2,
        traces_per_entry=24, seed=7, time_span_ms=SPAN_MS,
        missing_resource_frac=0.0,
        ensure_pattern_coverage_before_ms=BOUNDS[0]))
    shards = shard_frames_by_window(synth.spans, synth.resources, BOUNDS)
    pre0 = preprocess(shards[0][0], shards[0][1], cfg.ingest)
    table0 = assemble(pre0, cfg.ingest)
    base = base_shard(pre0, table0, cfg.graph_type, cfg.ingest)
    deltas = [ingest_delta(s, r, base, cfg.graph_type, cfg.ingest)
              for s, r in shards[1:]]
    oracle_ds, oracle_info = merge_shards(base, list(deltas), cfg)
    return cfg, base, deltas, oracle_ds, oracle_info


@pytest.fixture(scope="module")
def trained(corpus):
    """(model, tx, batches, state) on the merged toy corpus."""
    cfg, _base, _deltas, ds, _info = corpus
    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = make_tx(cfg)
    batches = list(ds.batches("train"))
    state = create_train_state(model, tx, batches[0], cfg.train.seed)
    return cfg, model, tx, batches, state


# -- shard-to-host assignment ---------------------------------------------


def test_assign_shards_partitions_exactly_once(corpus):
    _cfg_, _base, deltas, _ds, _info = corpus
    for hosts in (1, 2, 3, 5):
        slices = scale.assign_shards(deltas, hosts)
        assert len(slices) == hosts
        flat = sorted(i for s in slices for i in s)
        assert flat == list(range(len(deltas)))


def test_assign_shards_permutation_invariant_reversed(corpus):
    """Deterministic fallback for environments without hypothesis."""
    _cfg_, _base, deltas, _ds, _info = corpus
    fwd = scale.assign_shards(deltas, 2)
    rev = scale.assign_shards(list(reversed(deltas)), 2)
    n = len(deltas)
    keyed_fwd = [sorted(canonical_key(deltas[i]) for i in s) for s in fwd]
    keyed_rev = [sorted(canonical_key(deltas[n - 1 - i]) for i in s)
                 for s in rev]
    assert keyed_fwd == keyed_rev
    assert (scale.assignment_fingerprint(deltas, 2)
            == scale.assignment_fingerprint(list(reversed(deltas)), 2))


def test_assign_shards_permutation_invariant_hypothesis(corpus):
    pytest.importorskip("hypothesis",
                        reason="property tests need the hypothesis "
                               "dev extra")
    from hypothesis import given, settings
    from hypothesis import strategies as st

    _cfg_, _base, deltas, _ds, _info = corpus
    n = len(deltas)

    @settings(max_examples=60, deadline=None)
    @given(perm=st.permutations(range(n)),
           hosts=st.integers(min_value=1, max_value=4))
    def prop(perm, hosts):
        shuffled = [deltas[i] for i in perm]
        ref = [sorted(canonical_key(deltas[i]) for i in s)
               for s in scale.assign_shards(deltas, hosts)]
        got = [sorted(canonical_key(shuffled[i]) for i in s)
               for s in scale.assign_shards(shuffled, hosts)]
        assert got == ref
        assert (scale.assignment_fingerprint(shuffled, hosts)
                == scale.assignment_fingerprint(deltas, hosts))

    prop()


def test_verify_assignment_refuses_mismatch(corpus):
    _cfg_, _base, deltas, _ds, _info = corpus
    fp = scale.assignment_fingerprint(deltas, 2)
    scale.verify_assignment([fp, fp, fp])  # agreement passes
    bus = Capture()
    with pytest.raises(scale.HostAssignmentMismatch):
        scale.verify_assignment([fp, "deadbeefdeadbeef"], bus)
    assert any(n == "scale.host_assignment_mismatch"
               for n, _v, _t in bus.counters)


def test_assign_shards_rejects_zero_hosts(corpus):
    _cfg_, _base, deltas, _ds, _info = corpus
    with pytest.raises(ValueError):
        scale.assign_shards(deltas, 0)


# -- the collective sharded merge -----------------------------------------


def _assert_same_dataset(a, b) -> None:
    assert set(a.splits) == set(b.splits)
    for name in a.splits:
        ba, bb = list(a.batches(name)), list(b.batches(name))
        assert len(ba) == len(bb), name
        for x, y in zip(ba, bb):
            for f in x._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(x, f)), np.asarray(getattr(y, f)),
                    err_msg=f"{name}:{f}")


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="needs the 2-device CPU test platform")
def test_sharded_merge_bit_identical_to_oracle(corpus):
    cfg, base, deltas, oracle_ds, oracle_info = corpus
    mesh = make_mesh(data=2, model=1, devices=jax.devices()[:2])
    for perm in ([0, 1, 2], [2, 0, 1], [1, 2, 0], [2, 1, 0]):
        for hosts in (1, 2, 3):
            ds, info = scale.sharded_merge(
                base, [deltas[i] for i in perm], cfg, mesh,
                num_hosts=hosts)
            _assert_same_dataset(ds, oracle_ds)
            assert info.shards == oracle_info.shards
            assert info.new_entries == oracle_info.new_entries
            assert info.new_topologies == oracle_info.new_topologies
            assert info.dropped_coverage == oracle_info.dropped_coverage
            assert (info.dropped_occurrence
                    == oracle_info.dropped_occurrence)
            assert info.meta.equals(oracle_info.meta)


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="needs the 2-device CPU test platform")
def test_sharded_merge_emits_telemetry(corpus):
    cfg, base, deltas, _ds, _info = corpus
    mesh = make_mesh(data=2, model=1, devices=jax.devices()[:2])
    bus = Capture()
    scale.sharded_merge(base, list(deltas), cfg, mesh, num_hosts=2,
                        bus=bus)
    assert any(n == "scale.merge_seconds" for n, _v, _t in bus.hists)
    assert any(n == "scale.merge_hosts" and v == 2
               for n, v, _t in bus.gauges)


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="needs the 2-device CPU test platform")
def test_sharded_merge_honors_scale_hosts_config(corpus):
    """--scale_hosts routes through the config when the caller passes no
    explicit host count (the mesh data axis is only the fallback)."""
    cfg, base, deltas, oracle_ds, _info = corpus
    mesh = make_mesh(data=2, model=1, devices=jax.devices()[:2])
    cfg3 = dataclasses.replace(cfg, scale=ScaleConfig(scale_hosts=3))
    bus = Capture()
    ds, _ = scale.sharded_merge(base, list(deltas), cfg3, mesh, bus=bus)
    assert any(n == "scale.merge_hosts" and v == 3
               for n, v, _t in bus.gauges)
    _assert_same_dataset(ds, oracle_ds)


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="needs the 2-device CPU test platform")
def test_sharded_merge_refuses_like_single_host(corpus):
    """Every oracle refusal refuses identically here — the guards are
    the same code (a delta coded against a DIFFERENT base)."""
    cfg, base, deltas, _ds, _info = corpus
    mesh = make_mesh(data=2, model=1, devices=jax.devices()[:2])
    stale = dataclasses.replace(deltas[0],
                                base_vocab_hash="0" * 16)
    bus = Capture()
    with pytest.raises(StreamRebuildRequired) as ei:
        scale.sharded_merge(base, [stale] + list(deltas[1:]), cfg, mesh,
                            bus=bus)
    assert ei.value.reason == "base_changed"
    with pytest.raises(StreamRebuildRequired):
        merge_shards(base, [stale] + list(deltas[1:]), cfg)
    assert any(n == "stream.rebuild" and t.get("reason") == "base_changed"
               for n, _v, t in bus.counters)


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="needs the 2-device CPU test platform")
def test_sharded_merge_requires_base_first(corpus):
    cfg, _base, deltas, _ds, _info = corpus
    mesh = make_mesh(data=2, model=1, devices=jax.devices()[:2])
    with pytest.raises(ValueError):
        scale.sharded_merge(deltas[0], list(deltas[1:]), cfg, mesh)


# -- SAR bucket accumulation ----------------------------------------------


def test_bucket_batches_pads_with_inert_clones(trained):
    _cfg_, _model, _tx, batches, _state = trained
    cap = len(batches) + 3
    stacked = scale.bucket_batches(batches, cap)
    assert jax.tree.leaves(stacked)[0].shape[0] == cap
    pad = np.asarray(stacked.graph_mask[len(batches):])
    assert not pad.any()
    assert not np.asarray(stacked.node_mask[len(batches):]).any()


def test_bucket_batches_overflow_refuses(trained):
    _cfg_, _model, _tx, batches, _state = trained
    bus = Capture()
    with pytest.raises(scale.AccumulationOverflow):
        scale.bucket_batches(batches, len(batches) - 1, bus=bus)
    (name, _v, tags), = [c for c in bus.counters
                         if c[0] == "scale.accum_overflow"]
    assert tags == {"need": len(batches), "capacity": len(batches) - 1}
    with pytest.raises(ValueError):
        scale.bucket_batches([], 4)


@pytest.mark.slow
def test_sar_grads_bitwise_equal_to_monolithic(trained):
    """THE acceptance assert: grad of the remat scan equals grad of the
    monolithic (all-residuals-live) scan at tolerance 0 in f32, at more
    than one capacity."""
    cfg, model, _tx, batches, state = trained
    for cap in (len(batches), len(batches) + 2):
        buckets = jax.tree.map(jnp.asarray,
                               scale.bucket_batches(batches, cap))
        g_remat = jax.jit(scale.sar_grads_fn(model, cfg, remat=True))(
            state.params, state.batch_stats, buckets)
        g_mono = jax.jit(scale.sar_grads_fn(model, cfg, remat=False))(
            state.params, state.batch_stats, buckets)
        flat_r = jax.tree.leaves(g_remat)
        flat_m = jax.tree.leaves(g_mono)
        assert all(np.array_equal(np.asarray(a), np.asarray(b))
                   for a, b in zip(flat_r, flat_m)), cap
        assert sum(float(np.abs(np.asarray(a)).sum())
                   for a in flat_r) > 0


def test_sar_capacity_is_the_only_compiled_dimension(trained):
    """Live bucket-count changes reuse ONE compiled program — only a
    capacity change compiles fresh."""
    cfg, model, tx, batches, state = trained
    step = scale.make_sar_train_step(model, cfg, tx, remat=True)
    cap = len(batches) + 2
    # the jitted step donates its state argument — hand it a copy so the
    # module-scoped fixture state survives for later tests
    st = jax.tree.map(jnp.array, state)
    for live in (len(batches), 2, 1):
        buckets = jax.tree.map(jnp.asarray,
                               scale.bucket_batches(batches[:live], cap))
        st, metrics = step(st, buckets)
    assert step._cache_size() == 1
    assert int(st.step) == 3
    assert float(metrics["count"]) > 0


@pytest.mark.slow
def test_sar_remat_temp_bytes_below_monolithic(trained):
    cfg, model, tx, batches, state = trained
    cap = len(batches) + 1
    abs_of = lambda t: jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.asarray(a).shape,
                                       np.asarray(a).dtype), t)
    abs_b = abs_of(scale.bucket_batches(batches, cap))
    abs_s = abs_of(state)
    remat = scale.step_temp_bytes(
        scale.make_sar_train_step(model, cfg, tx, remat=True),
        abs_s, abs_b)
    mono = scale.step_temp_bytes(
        scale.make_sar_train_step(model, cfg, tx, remat=False),
        abs_s, abs_b)
    assert remat is not None and mono is not None
    assert remat < mono, (remat, mono)


def test_sample_bucket_memory_gauges(monkeypatch):
    """device.mem.peak_bytes rides the bucket tag (monkeypatched stats
    — CPU publishes none); the None-safe no-op path stays silent."""
    from pertgnn_tpu.telemetry import devmem

    bus = Capture()
    monkeypatch.setattr(devmem, "device_memory_stats",
                        lambda device=None: {"bytes_in_use": 10,
                                             "peak_bytes": 99,
                                             "bytes_limit": 1000})
    out = scale.sample_bucket_memory(bus, buckets=4)
    assert out["peak_bytes"] == 99
    (name, value, tags), = [g for g in bus.gauges
                            if g[0] == "device.mem.peak_bytes"]
    assert value == 99 and tags["buckets"] == 4
    monkeypatch.setattr(devmem, "device_memory_stats",
                        lambda device=None: None)
    bus2 = Capture()
    assert scale.sample_bucket_memory(bus2, buckets=4) is None
    assert not bus2.gauges


# -- fit() integration -----------------------------------------------------


@pytest.mark.slow
def test_fit_sar_path_trains_and_matches_metrics(corpus):
    """fit() with accum_buckets > 1 runs one accumulated step per epoch
    over the whole train mixture and reports finite metrics; the mesh
    combination refuses."""
    cfg, _base, _deltas, ds, _info = corpus
    sar_cfg = dataclasses.replace(
        cfg, scale=ScaleConfig(accum_buckets=len(list(
            ds.batches("train"))) + 1))
    state, history = fit(ds, sar_cfg)
    assert len(history) == sar_cfg.train.epochs
    assert int(state.step) == sar_cfg.train.epochs
    assert np.isfinite(history[-1]["train_qloss"])


def test_fit_refuses_mesh_with_accum_buckets(corpus):
    cfg, _base, _deltas, ds, _info = corpus
    sar_cfg = dataclasses.replace(cfg, scale=ScaleConfig(accum_buckets=2))
    mesh = make_mesh(data=2, model=1, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="accum_buckets"):
        fit(ds, sar_cfg, mesh=mesh)


def test_device_materialize_resolves_off_under_sar(corpus):
    from pertgnn_tpu.train.loop import _resolve_device_materialize

    cfg, _base, _deltas, ds, _info = corpus
    on = dataclasses.replace(
        cfg, scale=ScaleConfig(accum_buckets=4),
        train=dataclasses.replace(cfg.train, device_materialize=True))
    assert _resolve_device_materialize(ds, on) is False
    off = dataclasses.replace(
        on, scale=ScaleConfig(accum_buckets=1))
    assert _resolve_device_materialize(ds, off) in (True, False)
