"""Worker for the real-multi-process CPU tests (tests/test_multihost.py).

Each process: 2 virtual CPU devices -> NPROC*2 global devices over NPROC
processes (2 by default in the suite; 4 in the opt-in scale-out test).
Runs (a) ONE host-packed sharded train step on the deterministic first
global batch, (b) one full fit() epoch through the device-materialized
multi-host path. Process 0 writes the metrics to the JSON path in argv so
the parent can compare against its own single-process run of the same
global batch (SURVEY.md §4 "Distributed").

Not named test_* on purpose: launched as a subprocess, not collected.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pertgnn_tpu.parallel import multihost

PORT, PID, NPROC, OUT, CKPT_DIR = (sys.argv[1], int(sys.argv[2]),
                                   int(sys.argv[3]), sys.argv[4],
                                   sys.argv[5])
assert multihost.initialize(f"localhost:{PORT}", NPROC, PID)
assert jax.process_count() == NPROC

import dataclasses

import numpy as np
import optax

from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import (Config, DataConfig, IngestConfig, ModelConfig,
                                TrainConfig)
from pertgnn_tpu.ingest import synthetic
from pertgnn_tpu.ingest.preprocess import preprocess
from pertgnn_tpu.models.pert_model import make_model
from pertgnn_tpu.parallel.data_parallel import make_sharded_train_step
from pertgnn_tpu.parallel.mesh import batch_shardings, make_mesh
from pertgnn_tpu.parallel.multihost import (assemble_global,
                                            host_grouped_batches)
from pertgnn_tpu.train.loop import create_train_state, fit

# Must mirror tests/test_multihost.py:_dataset_and_cfg exactly — every
# process (and the single-process parent) builds the identical dataset.
cfg = Config(
    ingest=IngestConfig(min_traces_per_entry=10),
    data=DataConfig(max_traces=200, batch_size=8),
    model=ModelConfig(hidden_channels=16, num_layers=2),
    train=TrainConfig(lr=1e-3, label_scale=1000.0, scan_chunk=1),
)
data = synthetic.generate(synthetic.SyntheticSpec(
    num_microservices=30, num_entries=3, patterns_per_entry=3,
    traces_per_entry=40, seed=7))
pre = preprocess(data.spans, data.resources, cfg.ingest)
ds = build_dataset(pre, cfg)

n_shards = NPROC * 2  # 2 virtual devices per process
mesh = make_mesh(data=n_shards, model=1)

# (a) one host-packed sharded step on the first global batch: this process
# materializes ONLY its own 2 shards
model = make_model(cfg.model, ds.num_ms, ds.num_entries, ds.num_interfaces,
                   ds.num_rpctypes)
tx = optax.adam(cfg.train.lr)
from pertgnn_tpu.batching.materialize import zero_masked_idx

filler = lambda b: zero_masked_idx(b, ds.arena(), ds.feat_arena())
local = next(iter(host_grouped_batches(
    ds.index_batches("train"), n_shards, ds.materializer("train"), filler)))
glob = assemble_global(local, batch_shardings(mesh))
init_host = next(ds.batches("train"))
from pertgnn_tpu.parallel.data_parallel import stack_batches

state = create_train_state(model, tx, stack_batches([init_host] * n_shards),
                           cfg.train.seed)
step, sh_state = make_sharded_train_step(model, cfg, tx, mesh, state)
sh_state, m = step(sh_state, glob)
result = {k: float(v) for k, v in m.items()}

# (b) full fit() epoch through the device-materialized multi-host path
cfg_fit = cfg.replace(train=dataclasses.replace(cfg.train, scan_chunk=2))
_, hist = fit(ds, cfg_fit, epochs=1, mesh=mesh)
result["fit_train_qloss"] = hist[-1]["train_qloss"]
assert np.isfinite(result["fit_train_qloss"])

# (c) DISTRIBUTED checkpoint round-trip: all processes save the sharded
# state cooperatively (orbax) and restore directly into mesh shardings
from pertgnn_tpu.train.checkpoint import CheckpointManager

mgr = CheckpointManager(CKPT_DIR, keep=1)
mgr.save(0, sh_state, {"qloss_sum": result["qloss_sum"]})
mgr.wait()
restored, start = mgr.maybe_restore(sh_state)
assert start == 1
k_live = sh_state.params["conv_0"]["query"]["kernel"]
k_rest = restored.params["conv_0"]["query"]["kernel"]
assert k_rest.sharding == k_live.sharding
np.testing.assert_array_equal(np.asarray(jax.device_get(k_rest)),
                              np.asarray(jax.device_get(k_live)))
mgr.close()
result["ckpt_roundtrip"] = True

if PID == 0:
    with open(OUT, "w") as f:
        json.dump(result, f)
print(f"worker {PID} done: {result}", flush=True)
