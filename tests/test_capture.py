"""graftprobe capture journal laws: resume, wedge diagnosis, stitching.

The axon relay grants sub-minute windows, so bench.py --capture
decomposes into journaled stages (telemetry/capture.py) and re-enters
at the first incomplete one. These tests pin the contract on CPU with
fake runners and an injected stage budget as the deterministic
mid-stage kill: a budget-killed capture resumes with ZERO re-run
journaled stages, the stitched result passes the same schema as a
single-window capture, corrupt journal lines are skipped loudly, and
the stitcher refuses fragments spanning incompatible commits/configs/
backends.
"""

import importlib.util
import json
import logging
import os
import time

import pytest

import bench
from pertgnn_tpu.telemetry import capture as cap
from pertgnn_tpu.telemetry import devmem
from pertgnn_tpu.telemetry.schema import load_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ROOFLINE_ROW = {
    "attention_impl": "segment", "dtype": "f32",
    "flops_per_graph": 1.0e6, "bytes_per_graph": 2.0e5,
    "mfu_pct": None, "mbu_pct": None, "roofline_graphs_per_s": None,
}

_CONFIG_FP = {"traces_per_entry": 48, "windows": 2,
              "attention_impl": "segment", "simulate": True}


def _append_run(journal, commit="cafe01", backend="cpu", config=None):
    journal.append(cap.RUN_EVENT, {
        "commit": commit, "dirty_worktree": False,
        "config": dict(config if config is not None else _CONFIG_FP),
        "backend": backend, "device_kind": "", "backend_fallback": False,
        "simulate": True})


def _make_runners(windows, counts):
    """Stage runners that count invocations and return the minimal
    fields the stitcher needs — the zero-re-run assertion reads
    `counts` across entries."""
    def bump(stage, fields):
        def run():
            counts[stage] = counts.get(stage, 0) + 1
            return dict(fields)
        return run

    runners = {
        "probe": bump("probe", {"backend": "cpu", "device_kind": ""}),
        "arena_warm": bump("arena_warm", {
            "train_graphs_per_epoch": 64, "traces_per_entry": 48,
            "backend": "cpu", "device_kind": "",
            "attention_impl": "segment", "serve_dtype": "f32"}),
        "precompile": bump("precompile", {"programs": 3}),
        "cost": bump("cost", {
            "flops_per_graph": 1.0e6, "bytes_per_graph": 2.0e5,
            "peak_flops_per_chip": None, "peak_hbm_bytes_per_s": None,
            "device_kind": "", "backend": "cpu"}),
        "baseline": bump("baseline",
                         {"baseline_torch_cpu_graphs_per_s": 100.0}),
    }
    for i in range(windows):
        runners[f"window:{i:02d}:fit"] = bump(
            f"window:{i:02d}:fit",
            {"graphs_per_s": 200.0 + i, "backend": "cpu",
             "roofline": dict(_ROOFLINE_ROW)})
        runners[f"window:{i:02d}:ceiling"] = bump(
            f"window:{i:02d}:ceiling",
            {"graphs_per_s": 400.0 + i, "backend": "cpu",
             "roofline": dict(_ROOFLINE_ROW)})
        runners[f"window:{i:02d}:compact"] = bump(
            f"window:{i:02d}:compact",
            {"graphs_per_s": 300.0 + i, "backend": "cpu"})
    return runners


# ---------------------------------------------------------------- resume


def test_stage_plan_and_window_parsing():
    plan = cap.stage_plan(2)
    assert plan[:5] == list(cap.SETUP_STAGES)
    assert plan[5:] == ["window:00:fit", "window:00:ceiling",
                        "window:00:compact", "window:01:fit",
                        "window:01:ceiling", "window:01:compact"]
    assert cap.window_of("window:07:fit") == (7, "fit")
    assert cap.window_of("probe") is None
    assert cap.window_of("window:xx:fit") is None
    # exit codes are a wire contract with tpu_watch.sh + CI
    assert cap.EXIT_WINDOW_CLOSED == 3 and cap.EXIT_WEDGED == 4


def test_budget_killed_twice_resumes_with_zero_reruns(tmp_path):
    """The acceptance drill: two budget-killed entries + one clean one
    complete the capture; every stage ran EXACTLY once and each entry
    re-entered at the first incomplete stage."""
    journal = cap.CaptureJournal(str(tmp_path / "journal.jsonl"))
    plan = cap.stage_plan(2)
    counts: dict = {}

    _append_run(journal)
    r1 = cap.CaptureRunner(journal, plan, _make_runners(2, counts),
                           budget_stages=3)
    assert r1.run() == cap.OUTCOME_WINDOW_CLOSED
    assert r1.stages_run == ["probe", "arena_warm", "precompile"]
    # the in-flight stage is journaled started -> aborted, and resume
    # re-enters exactly there
    assert cap.first_incomplete(plan, journal.records()) == "cost"

    _append_run(journal)
    r2 = cap.CaptureRunner(journal, plan, _make_runners(2, counts),
                           budget_stages=3)
    assert r2.run() == cap.OUTCOME_WINDOW_CLOSED
    assert r2.stages_run == ["cost", "baseline", "window:00:fit"]
    assert (cap.first_incomplete(plan, journal.records())
            == "window:00:ceiling")

    _append_run(journal)
    r3 = cap.CaptureRunner(journal, plan, _make_runners(2, counts))
    assert r3.run() == cap.OUTCOME_COMPLETE
    assert cap.first_incomplete(plan, journal.records()) is None

    # zero re-runs: every stage's runner fired exactly once across all
    # three entries, and the journal holds exactly one done record each
    assert counts == {s: 1 for s in plan}
    done_counts: dict = {}
    for r in cap.stage_records(journal.records()):
        f = r["fields"]
        if f["status"] == cap.STATUS_DONE:
            done_counts[f["stage"]] = done_counts.get(f["stage"], 0) + 1
    assert done_counts == {s: 1 for s in plan}

    # every journal line is a strict schema-v2 event
    assert len(load_events(journal.path, strict=True)) > 0

    # the stitched result passes the same schema checks as a live
    # single-window capture (assembled by the same function)
    st = cap.stitch_windows(journal.records())
    assert st["complete"] is True
    assert st["fit_w"] == [200.0, 201.0]
    result = bench._assemble_from_stitch(st)
    assert result["stitched"] is True
    assert result["value"] == 200.5  # median of the stitched fit windows
    assert result["vs_baseline"] == pytest.approx(2.0, abs=0.02)
    assert len(result["windows_provenance"]) == 6
    assert result["capture_entries"] == 3


def test_aborted_stage_journal_shows_in_flight_step(tmp_path):
    journal = cap.CaptureJournal(str(tmp_path / "journal.jsonl"))
    _append_run(journal)
    counts: dict = {}
    runner = cap.CaptureRunner(journal, cap.stage_plan(1),
                               _make_runners(1, counts), budget_stages=1)
    assert runner.run() == cap.OUTCOME_WINDOW_CLOSED
    statuses = [(r["fields"]["stage"], r["fields"]["status"])
                for r in cap.stage_records(journal.records())]
    # the window closed with arena_warm in flight: started then aborted
    assert statuses[-2:] == [("arena_warm", cap.STATUS_STARTED),
                             ("arena_warm", cap.STATUS_ABORTED)]
    assert counts == {"probe": 1}


def test_wall_budget_closes_window(tmp_path):
    journal = cap.CaptureJournal(str(tmp_path / "journal.jsonl"))
    _append_run(journal)
    clock = {"t": 0.0}

    def fake_clock():
        return clock["t"]

    counts: dict = {}
    runners = _make_runners(1, counts)
    orig = runners["probe"]

    def slow_probe():
        clock["t"] += 99.0  # the stage eats the whole window
        return orig()
    runners["probe"] = slow_probe

    runner = cap.CaptureRunner(journal, cap.stage_plan(1), runners,
                               budget_s=60.0, clock=fake_clock)
    assert runner.run() == cap.OUTCOME_WINDOW_CLOSED
    last = cap.stage_records(journal.records())[-1]["fields"]
    assert last == {"stage": "arena_warm", "status": cap.STATUS_ABORTED,
                    "reason": "wall_budget"}


# ------------------------------------------------------ journal reading


def test_corrupt_lines_skipped_loudly(tmp_path, caplog):
    path = tmp_path / "journal.jsonl"
    journal = cap.CaptureJournal(str(path))
    _append_run(journal)
    journal.stage("probe", cap.STATUS_DONE, seconds=0.1)
    with open(path, "a") as f:
        f.write('{"not": "an event"}\n')       # decodes, fails schema
        f.write('{"v": 2, "t": 1.0, "tm"\n')   # torn mid-write
    journal.stage("arena_warm", cap.STATUS_DONE, seconds=0.2)
    with caplog.at_level(logging.WARNING,
                         logger="pertgnn_tpu.telemetry.capture"):
        records = journal.records()
    assert len(records) == 3
    assert journal.skipped_lines == 2
    assert sum("skipping bad line" in r.message
               for r in caplog.records) == 2
    # a torn tail never loses the good prefix
    assert set(cap.completed_stages(records)) == {"probe", "arena_warm"}


def test_missing_journal_reads_empty(tmp_path):
    journal = cap.CaptureJournal(str(tmp_path / "nope.jsonl"))
    assert journal.records() == []
    assert cap.first_incomplete(cap.stage_plan(1), []) == "probe"


# ------------------------------------------------------ wedge diagnosis


def test_orphaned_start_marked_wedged_and_rerun(tmp_path):
    """A hard-killed entry leaves a `started` record with no outcome;
    the next entry journals it wedged (stage name survives for the
    watcher) and the stage re-runs."""
    journal = cap.CaptureJournal(str(tmp_path / "journal.jsonl"))
    _append_run(journal)
    journal.stage("probe", cap.STATUS_STARTED)  # the killed entry

    assert cap.orphaned_stages(journal.records()) == ["probe"]
    counts: dict = {}
    runner = cap.CaptureRunner(journal, ["probe"],
                               _make_runners(1, counts))
    assert runner.run() == cap.OUTCOME_COMPLETE
    records = journal.records()
    assert cap.wedged_stages(records) == ["probe"]
    wedge = [r["fields"] for r in cap.stage_records(records)
             if r["fields"]["status"] == cap.STATUS_WEDGED]
    assert wedge[0]["reason"] == "orphaned_start"
    assert counts == {"probe": 1}  # orphan diagnosis does not skip it
    assert cap.orphaned_stages(records) == []


def test_watchdog_sigalrm_journals_wedge_and_dumps(tmp_path):
    """A stage sleeping past the watchdog is journaled `wedged` with an
    all-thread stack dump; the runner exits resumable (OUTCOME_WEDGED)
    and the faulthandler backstop is cancelled before it can kill the
    test process."""
    journal = cap.CaptureJournal(str(tmp_path / "journal.jsonl"))
    dump = tmp_path / "wedge.txt"

    def sleeper():
        time.sleep(30)  # interruptible wait, like a polling device op

    runner = cap.CaptureRunner(journal, ["probe"], {"probe": sleeper},
                               watchdog_s=0.2, dump_path=str(dump))
    t0 = time.monotonic()
    assert runner.run() == cap.OUTCOME_WEDGED
    assert time.monotonic() - t0 < 10  # the alarm, not the sleep
    wedge = [r["fields"] for r in cap.stage_records(journal.records())
             if r["fields"]["status"] == cap.STATUS_WEDGED]
    assert wedge and wedge[0]["reason"] == "watchdog_sigalrm"
    assert wedge[0]["stage"] == "probe"
    # the dump file holds the armed marker + the thread stacks
    text = dump.read_text()
    assert "stage probe armed" in text
    assert "Thread" in text or "File" in text
    # the stage stays incomplete: resume re-enters it
    assert cap.first_incomplete(["probe"], journal.records()) == "probe"
    # give the cancelled 2x backstop's window a beat — if cancellation
    # failed this test run would die here, loudly
    time.sleep(0.5)


# ------------------------------------------------------------- stitching


def _ev(name, fields, t=1_000_000.0, pid=41):
    return {"v": 2, "t": t, "tm": t, "pid": pid, "pi": 0,
            "kind": "meta", "name": name, "fields": fields}


def _stage_ev(stage, t=1_000_000.0, pid=41, **fields):
    payload = {"stage": stage, "status": cap.STATUS_DONE}
    win = cap.window_of(stage)
    if win is not None:
        payload["window"] = win[0]
    payload.update(fields)
    return _ev(cap.STAGE_EVENT, payload, t=t, pid=pid)


def _fake_journal(windows=2, backend="cpu", commit="cafe01",
                  t0=1_000_000.0):
    cfg = dict(_CONFIG_FP, windows=windows)
    recs = [_ev(cap.RUN_EVENT, {
        "commit": commit, "dirty_worktree": False, "config": cfg,
        "backend": backend, "device_kind": "", "backend_fallback": False,
        "simulate": True}, t=t0)]
    recs += [
        _stage_ev("probe", t=t0 + 1, backend=backend),
        _stage_ev("arena_warm", t=t0 + 2, backend=backend,
                  train_graphs_per_epoch=64, attention_impl="segment",
                  serve_dtype="f32", device_kind=""),
        _stage_ev("precompile", t=t0 + 3),
        _stage_ev("cost", t=t0 + 4, flops_per_graph=1.0e6,
                  bytes_per_graph=2.0e5, peak_flops_per_chip=None,
                  peak_hbm_bytes_per_s=None, device_kind="",
                  backend=backend),
        _stage_ev("baseline", t=t0 + 5,
                  baseline_torch_cpu_graphs_per_s=100.0),
    ]
    for i in range(windows):
        tw = t0 + 10 + 10 * i
        recs.append(_stage_ev(f"window:{i:02d}:fit", t=tw, pid=41 + i,
                              graphs_per_s=200.0 + i, backend=backend,
                              roofline=dict(_ROOFLINE_ROW)))
        recs.append(_stage_ev(f"window:{i:02d}:ceiling", t=tw + 1,
                              pid=41 + i, graphs_per_s=400.0 + i,
                              backend=backend))
        recs.append(_stage_ev(f"window:{i:02d}:compact", t=tw + 2,
                              pid=41 + i, graphs_per_s=300.0 + i,
                              backend=backend))
    return recs


def test_stitch_assembles_provenance_and_attribution():
    st = cap.stitch_windows(_fake_journal(3), min_fit_windows=3)
    assert st["complete"] is True
    assert st["fit_w"] == [200.0, 201.0, 202.0]
    assert st["ceil_w"] == [400.0, 401.0, 402.0]
    assert st["baseline"] == 100.0
    # per-window provenance: window id, stage, wall time, capturing pid
    assert [(p["window"], p["stage"]) for p in st["provenance"]] == [
        (i, k) for i in range(3) for k in ("fit", "ceiling", "compact")]
    assert {p["pid"] for p in st["provenance"]} == {41, 42, 43}
    # one roofline attribution row per fit window, flops/bytes non-null
    # (mfu/mbu honestly null off-chip)
    assert [a["window"] for a in st["window_attribution"]] == [0, 1, 2]
    for a in st["window_attribution"]:
        assert a["flops_per_graph"] is not None
        assert a["bytes_per_graph"] is not None
        assert a["mfu_pct"] is None

    result = bench._assemble_from_stitch(st)
    # same schema as a live capture: every _assemble_result field rides
    live = bench._assemble_result(
        fit_w=[1.0, 2.0, 3.0], ceil_w=[], cceil_w=[], unstaged_w=[],
        flops_per_graph=None, bytes_per_graph=None, baseline=1.0,
        backend="cpu", fallback=False, train_graphs=1)
    assert set(live) <= set(result)
    assert result["stitched"] is True
    assert result["value"] == 201.0
    assert "partial_capture" not in result  # complete stitch


def test_stitch_refuses_mixed_commits():
    recs = _fake_journal(2)
    recs += _fake_journal(2, commit="deadbeef")
    with pytest.raises(cap.StitchRefused, match="incompatible"):
        cap.stitch_windows(recs)


def test_stitch_refuses_mixed_configs():
    recs = _fake_journal(2)
    other = dict(_CONFIG_FP, windows=2, traces_per_entry=999)
    recs.append(_ev(cap.RUN_EVENT, {
        "commit": "cafe01", "config": other, "backend": "cpu"}))
    with pytest.raises(cap.StitchRefused, match="incompatible"):
        cap.stitch_windows(recs)


def test_stitch_refuses_mixed_window_backends():
    # window 00 captured on cpu, window 01 on tpu: fragments from
    # different chips must never form one number
    recs = [r for r in _fake_journal(2)
            if not (r["name"] == cap.STAGE_EVENT
                    and r["fields"].get("window") == 1)]
    recs.append(_stage_ev("window:01:fit", t=1_000_500.0,
                          graphs_per_s=999.0, backend="tpu"))
    with pytest.raises(cap.StitchRefused, match="backends"):
        cap.stitch_windows(recs, min_fit_windows=1)


def test_stitch_refuses_missing_baseline_and_identity():
    recs = [r for r in _fake_journal(2)
            if r["fields"].get("stage") != "baseline"]
    with pytest.raises(cap.StitchRefused, match="baseline"):
        cap.stitch_windows(recs)
    no_run = [r for r in _fake_journal(2) if r["name"] != cap.RUN_EVENT]
    with pytest.raises(cap.StitchRefused, match="identity"):
        cap.stitch_windows(no_run)


def test_stitch_refuses_too_few_windows():
    recs = _fake_journal(1)
    with pytest.raises(cap.StitchRefused, match="fit windows"):
        cap.stitch_windows(recs, min_fit_windows=3)


def test_stitch_drops_stale_windows_loudly():
    """A window >48h older than the newest fragment is dropped (and
    counted) rather than silently averaged into the number."""
    recs = _fake_journal(2)
    # push window 01 far into the future: window 00 becomes stale
    for r in recs:
        if (r["name"] == cap.STAGE_EVENT
                and r["fields"].get("window") == 1):
            r["t"] += 50 * 3600.0
    st = cap.stitch_windows(recs, min_fit_windows=1)
    assert st["stale_windows_dropped"] == 1
    assert st["fit_w"] == [201.0]  # only the fresh window
    assert st["complete"] is False
    assert bench._assemble_from_stitch(st)["partial_capture"] is True


def test_run_fingerprint_tracks_last_run():
    recs = _fake_journal(2)
    fp1 = cap.run_fingerprint(recs)
    assert fp1 is not None and fp1[0] == "cafe01" and fp1[2] == "cpu"
    recs.append(_ev(cap.RUN_EVENT, {"commit": "deadbeef",
                                    "config": _CONFIG_FP,
                                    "backend": "tpu"}))
    fp2 = cap.run_fingerprint(recs)
    assert fp2[0] == "deadbeef" and fp2[2] == "tpu"
    assert cap.run_fingerprint([]) is None


# ------------------------------------------------- probe availability


def test_probe_journal_and_availability_stats(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    for ok, lat in ((True, 0.2), (True, 0.3), (False, 5.0), (True, 0.1)):
        cap.journal_probe(path, ok=ok, latency_s=lat)
    records = cap.CaptureJournal(path).records()
    stats = cap.probe_availability(records)
    assert stats["probe_attempts"] == 4
    assert stats["probe_ok"] == 3
    assert stats["availability_pct"] == 75.0
    # consecutive ok probes form windows: (ok, ok), (ok)
    assert stats["healthy_windows"] == 2
    assert stats["window_histogram"]["lt_60s"] == 2
    assert stats["median_probe_latency_s"] == 0.3
    # an empty journal yields zeroed stats, not a crash
    empty = cap.probe_availability([])
    assert empty["probe_attempts"] == 0
    assert empty["availability_pct"] is None


# --------------------------------------------------------- devmem gauges


class _FakeDevice:
    def __init__(self, raw):
        self._raw = raw

    def memory_stats(self):
        if isinstance(self._raw, Exception):
            raise self._raw
        return self._raw


class _FakeBus:
    def __init__(self):
        self.gauges = []

    def gauge(self, name, value, **tags):
        self.gauges.append((name, value, tags))


def test_devmem_maps_pjrt_keys_and_emits_gauges():
    dev = _FakeDevice({"bytes_in_use": 10, "peak_bytes_in_use": 20,
                       "bytes_limit": 30, "num_allocs": 7})
    assert devmem.device_memory_stats(dev) == {
        "bytes_in_use": 10, "peak_bytes": 20, "bytes_limit": 30}
    bus = _FakeBus()
    stats = devmem.sample_device_memory(bus, dev, where="test")
    assert stats["peak_bytes"] == 20
    assert [(n, v) for n, v, _ in bus.gauges] == [
        ("device.mem.bytes_in_use", 10), ("device.mem.peak_bytes", 20),
        ("device.mem.bytes_limit", 30)]
    assert all(t == {"where": "test"} for _, _, t in bus.gauges)


def test_devmem_none_safe_on_cpu_like_devices():
    bus = _FakeBus()
    # raises -> None (some PJRT clients raise instead of returning None)
    assert devmem.device_memory_stats(
        _FakeDevice(RuntimeError("unimplemented"))) is None
    # returns None / empty -> None
    assert devmem.device_memory_stats(_FakeDevice(None)) is None
    assert devmem.device_memory_stats(_FakeDevice({})) is None
    # no memory_stats attribute at all -> None
    assert devmem.device_memory_stats(object()) is None
    # nothing emitted in any of those cases
    assert devmem.sample_device_memory(
        bus, _FakeDevice(None), where="t") is None
    assert bus.gauges == []


# -------------------------------------------------- adjudicate --stitch


@pytest.fixture
def adjudicate():
    spec = importlib.util.spec_from_file_location(
        "adjudicate_under_test",
        os.path.join(REPO, "benchmarks", "adjudicate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_journal(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_adjudicate_stitch_assembles_valid_journal(adjudicate, tmp_path,
                                                   capsys):
    path = str(tmp_path / "journal.jsonl")
    _write_journal(path, _fake_journal(3))
    assert adjudicate.stitch_main(["--stitch", "--journal", path]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["stitched"] is True
    assert out["value"] == 201.0
    assert len(out["windows_provenance"]) == 9
    assert [a["window"] for a in out["window_attribution"]] == [0, 1, 2]


def test_adjudicate_stitch_refuses_incompatible_fragments(adjudicate,
                                                          tmp_path,
                                                          capsys):
    path = str(tmp_path / "journal.jsonl")
    _write_journal(path, _fake_journal(2)
                   + _fake_journal(2, commit="deadbeef"))
    assert adjudicate.stitch_main(["--stitch", "--journal", path]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["stitched"] is False
    assert "incompatible" in out["refused"]


def test_adjudicate_stitch_missing_journal(adjudicate, tmp_path, capsys):
    path = str(tmp_path / "absent.jsonl")
    assert adjudicate.stitch_main(["--stitch", "--journal", path]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["stitched"] is False and "no capture journal" in out["refused"]
