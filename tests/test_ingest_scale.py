"""Reduced-scale regression for the on-disk ingest harness
(benchmarks/ingest_scale_r4.py; full-scale measurement in RESULTS.md).

Pins: the tiled tree builder writes the raw layout, the CLI preprocesses
it end-to-end in a child process, RSS sampling works, and entries
survive the occurrence filter across tiles (the tiling property the
multi-GB proof rests on).
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ingest_scale_harness_small(tmp_path):
    out = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "benchmarks", "ingest_scale_r4.py"),
         "--gb", "0.02", "--keep-tree", str(tmp_path / "tree")],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["rc"] == 0
    assert row["tiles"] >= 2                  # tiling actually happened
    assert row["raw_traces"] >= 30_000
    assert row["traces_per_s"] > 500          # CLI really processed them
    assert row["peak_rss_gb"] > 0             # RSS sampling produced data
    # artifacts landed (idempotent-cache layout)
    art = tmp_path / "tree" / "processed"
    assert (art / "trace_meta.parquet").exists()


def test_runtime_ids_numeric_equals_string_corpus():
    """The packed-token fast path must produce the EXACT runtime ids of
    the literal corpus-string path (assemble's fallback). Forced A/B on
    the same frame — nothing else exercises the string path now that
    factorized frames are always integer."""
    import numpy as np
    import pandas as pd

    from pertgnn_tpu.ingest.assemble import _runtime_ids_numeric

    rng = np.random.default_rng(0)
    rows = []
    for t in range(200):
        for _ in range(int(rng.integers(1, 7))):
            rows.append((t, int(rng.integers(0, 9)),
                         int(rng.integers(0, 9)), int(rng.integers(0, 5))))
    df = pd.DataFrame(rows, columns=["traceid", "um", "dm", "interface"])
    fast = _runtime_ids_numeric(df)
    token = (df["um"].astype(str) + "_" + df["dm"].astype(str)
             + "_" + df["interface"].astype(str))
    corpus = token.groupby(df["traceid"]).agg(" ".join)
    slow_codes, _ = pd.factorize(corpus)
    assert fast is not None
    np.testing.assert_array_equal(fast.index.to_numpy(),
                                  corpus.index.to_numpy())
    np.testing.assert_array_equal(fast.to_numpy(), slow_codes)
    # non-integer column -> declines, caller falls back
    df2 = df.assign(interface=df["interface"].astype(str))
    assert _runtime_ids_numeric(df2) is None


def test_coverage_filter_fast_path_equals_general():
    """Packed-int64 coverage filter == the pandas concat path on the
    same numeric frame (and the general path still serves raw ids the
    packing bounds exclude)."""
    import numpy as np
    import pandas as pd

    from pertgnn_tpu.config import IngestConfig
    from pertgnn_tpu.ingest.preprocess import filter_by_resource_coverage

    rng = np.random.default_rng(1)
    n = 3000
    df = pd.DataFrame({
        "traceid": rng.integers(0, 300, n),
        "um": rng.integers(0, 40, n),
        "dm": rng.integers(0, 40, n),
    })
    res = pd.DataFrame({"msname": np.arange(0, 40, 2)})
    cfg = IngestConfig(min_resource_coverage=0.6)
    # pin that the packed path actually runs for the base frame: the
    # general path's pandas concat must never be reached
    import unittest.mock as mock
    with mock.patch.object(pd, "concat",
                           side_effect=AssertionError("general path ran")):
        fast = filter_by_resource_coverage(df, res, cfg)
    # force the general path by giving um ids beyond the packing bound,
    # then map back — same structure, same surviving traces
    big = df.assign(um=df["um"] + 2**33, dm=df["dm"] + 2**33)
    res_big = res.assign(msname=res["msname"] + 2**33)
    slow = filter_by_resource_coverage(big, res_big, cfg)
    np.testing.assert_array_equal(
        np.sort(fast["traceid"].unique()),
        np.sort(slow["traceid"].unique()))
    assert len(fast) == len(slow)


def test_stream_vocab_nan_and_merge():
    """StreamVocab: NaN normalizes to the literal 'nan' (no -1 sentinel
    aliasing), codes are stable across shards, all-NaN shards encode."""
    import numpy as np
    import pandas as pd

    from pertgnn_tpu.ingest.io import StreamVocab

    v = StreamVocab()
    a = v.encode(pd.Series(["x", None, "y", "x"]))
    b = v.encode(pd.Series([None, "y"], dtype=object))
    c = v.encode(pd.Series([np.nan, np.nan], dtype=float))  # all-NaN
    assert (a >= 0).all() and (b >= 0).all() and (c >= 0).all()
    nan_code = v.map["nan"]
    assert a[1] == nan_code and b[0] == nan_code
    assert (c == nan_code).all()
    assert a[0] == a[3] == v.map["x"]
    assert a[2] == b[1] == v.map["y"]


def test_streaming_isomorphic(tmp_path):
    """The 200GB-scale streaming loader (per-shard factorization,
    numeric-only RAM) must produce a pipeline output ISOMORPHIC to the
    exact path's: same per-raw-trace (y, ts_bucket), the same partition
    of traces into entries and into runtime patterns, and the same
    mixture probabilities — only the opaque id labels may differ."""
    import numpy as np

    from pertgnn_tpu.config import Config, IngestConfig
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.assemble import assemble
    from pertgnn_tpu.ingest.io import (load_raw_csvs,
                                       load_raw_csvs_streaming)
    from pertgnn_tpu.ingest.preprocess import preprocess

    data = synthetic.generate(synthetic.SyntheticSpec(
        num_entries=4, traces_per_entry=40, seed=9))
    synthetic.write_csvs(data, str(tmp_path / "data"), shards=3)
    cfg = Config(ingest=IngestConfig(min_traces_per_entry=10))

    spans_e, res_e = load_raw_csvs(str(tmp_path / "data"))
    pre_e = preprocess(spans_e, res_e, cfg.ingest)
    tab_e = assemble(pre_e, cfg.ingest)

    spans_s, res_s, cfg_s, vocabs = load_raw_csvs_streaming(
        str(tmp_path / "data"), cfg.ingest)
    assert spans_s.select_dtypes(include=object).empty  # numeric-only
    pre_s = preprocess(spans_s, res_s, cfg_s)
    tab_s = assemble(pre_s, cfg_s)

    def by_raw_trace(pre, tab, raw_of_code):
        out = {}
        for _, row in tab.meta.iterrows():
            raw = raw_of_code(int(row["traceid"]))
            out[raw] = (np.float32(row["y"]), int(row["ts_bucket"]),
                        int(row["entry_id"]), int(row["runtime_id"]))
        return out

    e_map = by_raw_trace(pre_e, tab_e,
                         lambda c: str(pre_e.traceid_vocab[c]))
    s_map = by_raw_trace(pre_s, tab_s,
                         lambda c: str(vocabs["traceid"].items[
                             int(pre_s.traceid_vocab[c])]))
    assert set(e_map) == set(s_map)
    part_entry_e, part_entry_s = {}, {}
    part_rt_e, part_rt_s = {}, {}
    for raw in e_map:
        ye, be, ee, re_ = e_map[raw]
        ys, bs, es, rs = s_map[raw]
        assert ye == ys and be == bs, raw   # identical labels/buckets
        part_entry_e.setdefault(ee, set()).add(raw)
        part_entry_s.setdefault(es, set()).add(raw)
        part_rt_e.setdefault(re_, set()).add(raw)
        part_rt_s.setdefault(rs, set()).add(raw)
    # same PARTITIONS (labels may permute)
    assert (sorted(map(frozenset, part_entry_e.values()))
            == sorted(map(frozenset, part_entry_s.values())))
    assert (sorted(map(frozenset, part_rt_e.values()))
            == sorted(map(frozenset, part_rt_s.values())))
    # mixture probabilities: same multiset of sorted prob vectors
    probs_e = sorted(tuple(np.round(np.sort(p), 12))
                     for _, p in tab_e.entry2runtimes.values())
    probs_s = sorted(tuple(np.round(np.sort(p), 12))
                     for _, p in tab_s.entry2runtimes.values())
    assert probs_e == probs_s


def test_parallel_streaming_equal(tmp_path):
    """workers>1 must be BYTE-IDENTICAL to workers=1 (VERDICT r4 #4):
    the pool only moves shard parse+factorize off the parent; the
    shard-order StreamVocab.merge in the parent fixes code assignment
    independently of worker count or completion order."""
    import pandas as pd

    from pertgnn_tpu.config import IngestConfig
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.io import load_raw_csvs_streaming

    data = synthetic.generate(synthetic.SyntheticSpec(
        num_entries=4, traces_per_entry=40, seed=9))
    synthetic.write_csvs(data, str(tmp_path / "data"), shards=5)
    cfg = IngestConfig(min_traces_per_entry=10)

    spans_1, res_1, cfg_1, voc_1 = load_raw_csvs_streaming(
        str(tmp_path / "data"), cfg, workers=1)
    spans_4, res_4, cfg_4, voc_4 = load_raw_csvs_streaming(
        str(tmp_path / "data"), cfg, workers=4)

    pd.testing.assert_frame_equal(spans_1, spans_4)
    pd.testing.assert_frame_equal(res_1, res_4)
    assert cfg_1 == cfg_4
    for name in voc_1:
        assert voc_1[name].items == voc_4[name].items, name
