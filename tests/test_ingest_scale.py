"""Reduced-scale regression for the on-disk ingest harness
(benchmarks/ingest_scale_r4.py; full-scale measurement in RESULTS.md).

Pins: the tiled tree builder writes the raw layout, the CLI preprocesses
it end-to-end in a child process, RSS sampling works, and entries
survive the occurrence filter across tiles (the tiling property the
multi-GB proof rests on).
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ingest_scale_harness_small(tmp_path):
    out = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "benchmarks", "ingest_scale_r4.py"),
         "--gb", "0.02", "--keep-tree", str(tmp_path / "tree")],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["rc"] == 0
    assert row["tiles"] >= 2                  # tiling actually happened
    assert row["raw_traces"] >= 30_000
    assert row["traces_per_s"] > 500          # CLI really processed them
    assert row["peak_rss_gb"] > 0             # RSS sampling produced data
    # artifacts landed (idempotent-cache layout)
    art = tmp_path / "tree" / "processed"
    assert (art / "trace_meta.parquet").exists()
