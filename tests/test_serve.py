"""Serving path (pertgnn_tpu/serve/): bucket ladder, AOT executable
cache, single-batch fast pack, and the microbatching queue.

The load-bearing guarantees:
- bucket selection always picks the SMALLEST fitting rung (pad waste is
  bounded by the ladder's growth factor only if this holds);
- padding a request up to a bucket must be unobservable — the padded
  output is bit-identical to the exact-shape (unpadded) forward;
- after warmup the executable cache never misses over a request stream
  spanning several shape buckets (steady-state serving never compiles);
- microbatch coalescing preserves per-request prediction alignment.
"""

import numpy as np
import pytest

from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.batching.pack import BatchBudget, pack_single
from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                ModelConfig, ServeConfig, TrainConfig)
from pertgnn_tpu.serve.buckets import (make_bucket_ladder, pad_waste,
                                       select_bucket)
from pertgnn_tpu.serve.engine import InferenceEngine, RequestTooLarge
from pertgnn_tpu.serve.queue import MicrobatchQueue
from pertgnn_tpu.train.loop import restore_target_state

SERVE = ServeConfig(bucket_growth=2.0, min_bucket_nodes=128,
                    min_bucket_edges=128, max_graphs_per_batch=8)


@pytest.fixture(scope="module")
def served(preprocessed):
    """(dataset, cfg, state, warmed engine) over the shared synthetic
    corpus — weights are a fresh init (serving behavior is independent of
    training quality; the e2e CLI test covers trained checkpoints)."""
    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=200, batch_size=16),
        model=ModelConfig(hidden_channels=16, num_layers=2),
        train=TrainConfig(label_scale=1000.0),
        serve=SERVE,
        graph_type="pert",
    )
    ds = build_dataset(preprocessed, cfg)
    _model, state = restore_target_state(ds, cfg)
    engine = InferenceEngine.from_dataset(ds, cfg, state).warmup()
    return ds, cfg, state, engine


class TestBucketLadder:
    def test_ladder_shape(self):
        top = BatchBudget(max_graphs=170, max_nodes=4096, max_edges=5120)
        ladder = make_bucket_ladder(top, SERVE)
        assert len(ladder) >= 3
        # ascending, 128-aligned, top rung covers the training budget
        for lo, hi in zip(ladder, ladder[1:]):
            assert lo.max_nodes <= hi.max_nodes
            assert lo.max_edges <= hi.max_edges
        for b in ladder:
            assert b.max_nodes % 128 == 0 and b.max_edges % 128 == 0
            assert b.max_graphs == SERVE.max_graphs_per_batch
        assert ladder[-1].max_nodes >= top.max_nodes
        assert ladder[-1].max_edges >= top.max_edges
        assert ladder[0].max_nodes <= SERVE.min_bucket_nodes

    def test_tiny_budget_single_rung(self):
        ladder = make_bucket_ladder(
            BatchBudget(max_graphs=4, max_nodes=128, max_edges=128), SERVE)
        assert len(ladder) == 1
        assert ladder[0].max_graphs == 4  # never exceeds the budget's

    def test_select_bucket_picks_smallest_fitting(self):
        top = BatchBudget(max_graphs=170, max_nodes=4096, max_edges=5120)
        ladder = make_bucket_ladder(top, SERVE)
        for g, n, e in [(1, 1, 1), (1, 128, 128), (1, 129, 1),
                        (8, 1000, 900), (3, 4096, 5120)]:
            idx = select_bucket(ladder, g, n, e)
            assert idx is not None
            b = ladder[idx]
            assert g <= b.max_graphs and n <= b.max_nodes and e <= b.max_edges
            # every smaller rung must NOT fit — "smallest" is the law
            for smaller in ladder[:idx]:
                assert (g > smaller.max_graphs or n > smaller.max_nodes
                        or e > smaller.max_edges)

    def test_select_bucket_none_when_oversized(self):
        ladder = make_bucket_ladder(
            BatchBudget(max_graphs=4, max_nodes=256, max_edges=256), SERVE)
        assert select_bucket(ladder, 1, 10_000, 1) is None
        assert select_bucket(ladder, 99, 1, 1) is None

    def test_pad_waste(self):
        b = BatchBudget(max_graphs=8, max_nodes=100, max_edges=100)
        assert pad_waste(b, 100, 100) == 0.0
        assert pad_waste(b, 50, 50) == pytest.approx(0.5)


class TestPackSingle:
    def test_rejects_overflow_and_empty(self, served):
        ds, cfg, _state, _engine = served
        tiny = BatchBudget(max_graphs=1, max_nodes=2, max_edges=1)
        s = ds.splits["test"]
        with pytest.raises(ValueError, match="do not fit"):
            pack_single(ds.mixtures, s.entry_ids[:1], s.ts_buckets[:1],
                        tiny, ds.lookup)
        with pytest.raises(ValueError, match="at least one"):
            pack_single(ds.mixtures, s.entry_ids[:0], s.ts_buckets[:0],
                        ds.budget, ds.lookup)

    def test_matches_epoch_packer_invariants(self, served):
        ds, cfg, _state, _engine = served
        s = ds.splits["test"]
        b = pack_single(ds.mixtures, s.entry_ids[:3], s.ts_buckets[:3],
                        ds.budget, ds.lookup)
        # receiver-sorted real edges, pad edges at the tail
        real = b.edge_mask.nonzero()[0]
        assert (np.diff(b.receivers[real]) >= 0).all()
        assert not b.edge_mask[len(real):].any()
        # pad nodes point at the reserved pad graph slot
        assert (b.node_graph[~b.node_mask] == b.num_graphs - 1).all()
        assert not b.graph_mask[-1]
        assert b.graph_mask[:3].all() and not b.graph_mask[3:].any()


class TestPaddingInvariance:
    def test_bucket_padding_is_bit_identical_to_unpadded(self, served):
        """The same request packed at exact shape (zero padding) and
        padded up to ANY ladder rung must produce bit-identical
        predictions — padding must be unobservable, not merely small.

        Compiled execution (what serving dispatches — jit here, the AOT
        twin in the engine cache) IS bit-stable across pad shapes; the
        eager trace is not (op-by-op reassociation differs by 1 ulp), so
        the assertion deliberately runs the compiled path."""
        import jax

        ds, cfg, _state, engine = served
        step = jax.jit(engine._step)
        s = ds.splits["test"]
        for k in (1, 3):
            entries, buckets = s.entry_ids[:k], s.ts_buckets[:k]
            n = sum(ds.mixtures[int(e)].num_nodes for e in entries)
            e_tot = sum(ds.mixtures[int(e)].num_edges for e in entries)
            exact = BatchBudget(max_graphs=k, max_nodes=n, max_edges=e_tot)
            outs = []
            for budget in [exact, *engine.ladder]:
                if (n > budget.max_nodes or e_tot > budget.max_edges
                        or k > budget.max_graphs):
                    continue
                batch = pack_single(ds.mixtures, entries, buckets, budget,
                                    ds.lookup)
                pred = np.asarray(step(engine._variables, batch))[:k]
                outs.append((budget, pred))
            assert len(outs) >= 3  # exact + at least two rungs
            ref_budget, ref = outs[0]
            assert ref_budget is exact
            for budget, out in outs[1:]:
                np.testing.assert_array_equal(
                    out, ref,
                    err_msg=f"padding to {budget} changed the prediction")

    def test_served_split_matches_offline_predict(self, served):
        """The bucketed request path must reproduce the epoch-packed
        offline prediction for a whole split."""
        from pertgnn_tpu.train.predict import (predict_split,
                                               predict_split_served)

        ds, cfg, state, engine = served
        off = predict_split(ds, cfg, state, "test")
        srv = predict_split_served(ds, cfg, state, "test", engine=engine)
        np.testing.assert_array_equal(srv, off)


class TestExecutableCache:
    def test_zero_misses_after_warmup(self, served):
        """A randomized stream spanning >= 3 shape buckets must be served
        entirely from the warmed executable cache."""
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        rng = np.random.default_rng(0)
        hits0, misses0 = engine.cache_hits, engine.cache_misses
        used = set()
        for _ in range(30):
            k = int(rng.integers(1, cfg.serve.max_graphs_per_batch + 1))
            idx = rng.integers(0, len(s.entry_ids), size=k)
            entries, buckets = s.entry_ids[idx], s.ts_buckets[idx]
            n = sum(ds.mixtures[int(e)].num_nodes for e in entries)
            e_tot = sum(ds.mixtures[int(e)].num_edges for e in entries)
            used.add(select_bucket(engine.ladder, k, n, e_tot))
            engine.predict_microbatch(entries, buckets)
        assert len(used) >= 3, (
            "stream too uniform to exercise the ladder — widen the "
            "microbatch size range")
        assert engine.cache_misses == misses0
        assert engine.cache_hits == hits0 + 30

    def test_compiles_once_per_rung(self, served):
        _ds, _cfg, _state, engine = served
        assert engine.compiles == len(engine.ladder)
        assert engine.warmup_s is not None

    def test_oversized_request_raises(self, served):
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        # enough copies of the largest mixture to overflow the top rung
        big = max(ds.mixtures, key=lambda k: ds.mixtures[k].num_nodes)
        reps = (engine.ladder[-1].max_nodes
                // ds.mixtures[big].num_nodes) + 1
        reps = min(reps, engine.ladder[-1].max_graphs + 1)
        with pytest.raises(RequestTooLarge):
            engine.predict_microbatch(
                np.full(reps, big), np.full(reps, s.ts_buckets[0]))

    def test_stats_schema(self, served):
        _ds, _cfg, _state, engine = served
        stats = engine.stats_dict()
        assert {"requests", "batches", "cache_hits", "cache_misses",
                "compiles", "warmup_s", "pad_waste_ratio", "latency",
                "buckets"} <= set(stats)
        assert 0.0 <= stats["pad_waste_ratio"] < 1.0
        assert {"p50_ms", "p95_ms", "p99_ms"} <= set(stats["latency"])
        assert len(stats["buckets"]) == len(engine.ladder)


class TestPaddingInvarianceMatrix:
    """Padding invariance across EVERY attention_impl x serve_dtype
    combination graftaudit enumerates (ISSUE 10): the static
    padding-taint pass proves lane-independence for segment and
    blocked_dense and stops at the pallas_call boundary
    (docs/LINTS.md), so this dynamic grid is the matching coverage —
    one bit-identical pad check per compiled serve program family, on
    CPU (Pallas in interpret mode). Plain "pallas" rides the `slow`
    marker like test_model's grid: its interpret-mode kernels are
    already parity-pinned at the kernel level in tier-1."""

    IMPLS = (pytest.param("pallas", marks=pytest.mark.slow),
             "segment", "pallas_fused", "blocked_dense")

    @pytest.mark.parametrize("serve_dtype", ["f32", "bf16", "int8"])
    @pytest.mark.parametrize("impl", IMPLS)
    def test_bucket_padding_bit_identical(self, served, impl,
                                          serve_dtype):
        import dataclasses

        import jax

        ds, cfg, state, _engine = served
        c = dataclasses.replace(
            cfg,
            serve=dataclasses.replace(cfg.serve,
                                      serve_dtype=serve_dtype),
            model=dataclasses.replace(cfg.model, attention_impl=impl))
        engine = InferenceEngine.from_dataset(ds, c, state)
        step = jax.jit(engine._step)
        s = ds.splits["test"]
        entries, buckets = s.entry_ids[:1], s.ts_buckets[:1]
        n = ds.mixtures[int(entries[0])].num_nodes
        e_tot = ds.mixtures[int(entries[0])].num_edges
        exact = BatchBudget(max_graphs=1, max_nodes=n, max_edges=e_tot)
        outs = []
        for budget in [exact, *engine.ladder[:2]]:
            if (n > budget.max_nodes or e_tot > budget.max_edges
                    or budget.max_graphs < 1):
                continue
            batch = pack_single(ds.mixtures, entries, buckets, budget,
                                ds.lookup)
            outs.append((budget,
                         np.asarray(step(engine._variables, batch))[:1]))
        assert len(outs) >= 2  # exact + at least one rung
        ref_budget, ref = outs[0]
        assert ref_budget is exact
        for budget, out in outs[1:]:
            np.testing.assert_array_equal(
                out, ref,
                err_msg=(f"{impl}/{serve_dtype}: padding to {budget} "
                         f"changed the prediction"))


class TestQuantizedServeTier:
    """ServeConfig.serve_dtype (ISSUE 6): the bf16/int8 engines serve
    through the same per-rung AOT path with predictions close to the f32
    engine — the HARD quality gate lives in benchmarks/serve_bench.py
    (quantile-loss delta, exit-code-asserted); these pin the mechanics
    and closeness on the shared corpus."""

    @pytest.fixture(scope="class", params=["bf16", "int8"])
    def quantized(self, request, served):
        import dataclasses

        ds, cfg, state, _engine = served
        cfg_q = cfg.replace(serve=dataclasses.replace(
            cfg.serve, serve_dtype=request.param))
        return request.param, ds, cfg_q, state, InferenceEngine.from_dataset(
            ds, cfg_q, state).warmup()

    def test_predictions_close_to_f32(self, served, quantized):
        ds, _cfg, _state, engine_f = served
        dtype, _ds, _cfg_q, _state_q, engine_q = quantized
        s = ds.splits["test"]
        n = min(len(s.entry_ids), 24)
        pf = engine_f.predict_many(s.entry_ids[:n], s.ts_buckets[:n])
        pq = engine_q.predict_many(s.entry_ids[:n], s.ts_buckets[:n])
        assert pq.shape == pf.shape
        assert np.isfinite(np.asarray(pq, np.float32)).all()
        # bf16 mantissa ~ 3 decimal digits; int8 weights add quant noise
        tol = 0.02 if dtype == "bf16" else 0.06
        scale = max(float(np.abs(np.asarray(pf)).max()), 1e-6)
        assert float(np.abs(np.asarray(pq, np.float32)
                            - np.asarray(pf, np.float32)).max()) <= \
            tol * scale, dtype

    def test_zero_cache_misses_after_warmup(self, quantized):
        dtype, ds, _cfg, _state, engine = quantized
        s = ds.splits["test"]
        engine.predict_many(s.entry_ids[:16], s.ts_buckets[:16])
        stats = engine.stats_dict()
        assert stats["cache_misses"] == 0, dtype

    def test_int8_params_live_as_int8_on_device(self, quantized):
        """The int8 engine's device-resident 2-D weights must BE int8
        (the HBM saving is the point) with per-channel f32 scales."""
        import jax.numpy as jnp

        dtype, _ds, _cfg, _state, engine = quantized
        if dtype != "int8":
            pytest.skip("int8-specific")
        leaves = []

        def walk(node):
            if isinstance(node, dict):
                if set(node) == {"int8", "scale"}:
                    leaves.append(node)
                else:
                    for v in node.values():
                        walk(v)

        walk(engine._variables["params"])
        assert leaves, "no quantized leaves on the int8 engine"
        for q in leaves:
            assert q["int8"].dtype == jnp.int8
            assert q["scale"].dtype == jnp.float32

    def test_unknown_dtype_rejected(self, served):
        import dataclasses

        ds, cfg, state, _engine = served
        bad = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                    serve_dtype="fp8"))
        with pytest.raises(ValueError, match="serve_dtype"):
            InferenceEngine.from_dataset(ds, bad, state)


class TestMicrobatchQueue:
    def test_coalescing_preserves_alignment(self, served):
        """Requests submitted concurrently and coalesced into shared
        batches must each get THEIR prediction — identical to serving the
        same request alone."""
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        k = min(12, len(s.entry_ids))
        solo = np.concatenate([
            engine.predict_microbatch(s.entry_ids[i:i + 1],
                                      s.ts_buckets[i:i + 1])
            for i in range(k)])
        batches0 = engine.batches
        with MicrobatchQueue(engine, flush_deadline_ms=25) as q:
            futs = [q.submit(int(s.entry_ids[i]), int(s.ts_buckets[i]))
                    for i in range(k)]
            got = np.asarray([f.result(timeout=30) for f in futs],
                             np.float32)
        np.testing.assert_array_equal(got, solo)
        # the deadline actually coalesced: far fewer dispatches than
        # requests (worst realistic case: a flush per capacity fill)
        assert engine.batches - batches0 < k

    def test_deadline_zero_serves_singly(self, served):
        ds, _cfg, _state, engine = served
        s = ds.splits["test"]
        with MicrobatchQueue(engine, flush_deadline_ms=0) as q:
            v = q.predict(int(s.entry_ids[0]), int(s.ts_buckets[0]))
        assert np.isfinite(v)

    def test_submit_after_close_raises(self, served):
        ds, _cfg, _state, engine = served
        s = ds.splits["test"]
        q = MicrobatchQueue(engine, flush_deadline_ms=1)
        q.close()
        with pytest.raises(RuntimeError, match="closed"):
            q.submit(int(s.entry_ids[0]), int(s.ts_buckets[0]))

    def test_unknown_entry_fails_caller_not_worker(self, served):
        _ds, _cfg, _state, engine = served
        with MicrobatchQueue(engine, flush_deadline_ms=1) as q:
            with pytest.raises(KeyError):
                q.submit(10_000_000, 0)


class TestOverlappedDispatch:
    """ServeConfig.overlap_dispatch: pack batch k+1 while the device
    computes k. The contract: bit-identical predictions, futures never
    held hostage (idle completion, close flush), and the phase-split
    engine API composes to exactly the synchronous call."""

    def test_engine_phases_compose_to_predict_microbatch(self, served):
        ds, _cfg, _state, engine = served
        s = ds.splits["test"]
        e = np.asarray(s.entry_ids[:3], np.int64)
        t = np.asarray(s.ts_buckets[:3], np.int64)
        whole = engine.predict_microbatch(e, t)
        packed = engine.pack_microbatch(e, t)
        phased = engine.complete_microbatch(engine.dispatch_packed(packed))
        np.testing.assert_array_equal(whole, phased)

    def test_overlap_bit_identical_to_sync(self, served):
        import threading

        ds, _cfg, _state, engine = served
        s = ds.splits["test"]
        k = min(24, len(s.entry_ids))
        solo = np.concatenate([
            engine.predict_microbatch(s.entry_ids[i:i + 1],
                                      s.ts_buckets[i:i + 1])
            for i in range(k)])

        def drive(q):
            preds = np.full(k, np.nan, np.float32)

            def client(idx):
                for i in idx:
                    preds[i] = q.predict(int(s.entry_ids[i]),
                                         int(s.ts_buckets[i]),
                                         timeout=60)
            threads = [threading.Thread(target=client,
                                        args=(range(c, k, 4),))
                       for c in range(4)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            return preds

        with MicrobatchQueue(engine, flush_deadline_ms=5,
                             overlap_dispatch=True) as q:
            over = drive(q)
            stats_over = q.stats_dict()
        with MicrobatchQueue(engine, flush_deadline_ms=5,
                             overlap_dispatch=False) as q:
            sync = drive(q)
            stats_sync = q.stats_dict()
        np.testing.assert_array_equal(over, solo)
        np.testing.assert_array_equal(sync, solo)
        assert stats_over["overlap_dispatch"] is True
        assert stats_over["overlapped"] >= 1
        assert stats_sync["overlapped"] == 0

    def test_inflight_completes_without_followup_traffic(self, served):
        """A dispatched-in-overlap batch must resolve promptly when NO
        further request ever arrives — the worker completes the
        in-flight batch before blocking on an empty queue."""
        ds, _cfg, _state, engine = served
        s = ds.splits["test"]
        with MicrobatchQueue(engine, flush_deadline_ms=1,
                             overlap_dispatch=True) as q:
            fut = q.submit(int(s.entry_ids[0]), int(s.ts_buckets[0]))
            v = fut.result(timeout=30)
        assert np.isfinite(v)

    def test_close_flushes_inflight(self, served):
        ds, _cfg, _state, engine = served
        s = ds.splits["test"]
        k = min(6, len(s.entry_ids))
        q = MicrobatchQueue(engine, flush_deadline_ms=1,
                            overlap_dispatch=True)
        futs = [q.submit(int(s.entry_ids[i]), int(s.ts_buckets[i]))
                for i in range(k)]
        q.close()
        for f in futs:
            assert np.isfinite(f.result(timeout=1))


def test_serve_cli_round_trip(tmp_path):
    """train_main writes a checkpoint; serve_main restores it and serves
    a split replay through the full queue+engine stack, emitting aligned
    predictions and the serving-metrics JSON line."""
    import json

    import pandas as pd

    from pertgnn_tpu.cli import serve_main, train_main

    ckpt = str(tmp_path / "ckpt")
    common = ["--synthetic", "--synthetic_entries", "2",
              "--synthetic_traces_per_entry", "60",
              "--min_traces_per_entry", "5", "--label_scale", "1000",
              "--artifact_dir", str(tmp_path / "art"),
              "--checkpoint_dir", ckpt]
    train_main.main([*common, "--epochs", "2"])
    out = str(tmp_path / "served.csv")
    serve_main.main([*common, "--from_split", "test", "--concurrency", "3",
                     "--flush_deadline_ms", "5", "--out", out],)
    df = pd.read_csv(out)
    assert set(df.columns) == {"entry_id", "ts_bucket", "y_pred"}
    assert len(df) > 0 and np.isfinite(df["y_pred"]).all()


def test_predict_cli_serve_bucketed_matches_offline(tmp_path):
    """--serve_bucketed must write the SAME predictions as the offline
    epoch-packed path (both CSVs row-aligned to the meta table)."""
    import pandas as pd

    from pertgnn_tpu.cli import predict_main, train_main

    ckpt = str(tmp_path / "ckpt")
    common = ["--synthetic", "--synthetic_entries", "2",
              "--synthetic_traces_per_entry", "60",
              "--min_traces_per_entry", "5", "--label_scale", "1000",
              "--artifact_dir", str(tmp_path / "art"),
              "--checkpoint_dir", ckpt]
    train_main.main([*common, "--epochs", "2"])
    off_csv = str(tmp_path / "off.csv")
    srv_csv = str(tmp_path / "srv.csv")
    predict_main.main([*common, "--split", "all", "--out", off_csv])
    predict_main.main([*common, "--split", "all", "--serve_bucketed",
                       "--out", srv_csv])
    off = pd.read_csv(off_csv)
    srv = pd.read_csv(srv_csv)
    assert (off["traceid"] == srv["traceid"]).all()
    np.testing.assert_allclose(srv["y_pred"], off["y_pred"],
                               rtol=1e-5, atol=1e-5)
