"""Property tests for the three packed-int64 key tricks (VERDICT r4 #7).

The same trick — pack a tuple of non-negative ids into one int64 so a
vectorized np.unique / Index.get_indexer replaces a pandas groupby — now
appears at three sites, each with its own bounds:

1. `ingest.preprocess.filter_by_resource_coverage` fast path:
   key = traceid << 32 | ms, needs ms < 2^32 and 0 <= traceid < 2^31.
2. `batching.featurize.ResourceLookup`: key = bucket * 2^22 + ms, needs
   0 <= ms < 2^22 and |bucket| < 2^40 (out-of-bounds queries/tables take
   a MultiIndex path; this bound check is the VERDICT r4 weak-#5 fix).
3. `ingest.assemble._runtime_ids_numeric`: dynamic-width token
   (um << b) | (dm << c) | ifc with sum(bit widths) <= 62, else the
   caller falls back to the literal string corpus.

Each property pins the packed path to an order-free oracle built the slow
way (string domains / Python dicts), over id ranges that STRADDLE the
bounds — so both the in-bounds correctness and the out-of-bounds
fallback are exercised by the same law: packed result == oracle result,
for every input.
"""

import numpy as np
import pandas as pd
import pytest

pytest.importorskip(
    "hypothesis",
    reason="property tests need the hypothesis dev extra "
           "(pip install -e .[dev])")
from hypothesis import given, settings
from hypothesis import strategies as st

from pertgnn_tpu.batching.featurize import ResourceLookup
from pertgnn_tpu.config import IngestConfig
from pertgnn_tpu.ingest.assemble import _runtime_ids_numeric
from pertgnn_tpu.ingest.preprocess import filter_by_resource_coverage
from pertgnn_tpu.ingest.schema import NUM_RESOURCE_FEATURES

# ---------------------------------------------------------------------------
# 1. coverage filter: packed fast path == string-domain general path
# ---------------------------------------------------------------------------

# ids straddling the fast path's bounds: small codes, 2^32 ms overflows,
# 2^31 traceids, negatives — the function must route each case correctly
_ms_id = st.integers(0, 6) | st.integers(2**32 - 2, 2**32 + 2)
_trace_id = st.integers(0, 4) | st.integers(2**31 - 1, 2**31 + 1)
_span_row = st.tuples(_trace_id, _ms_id, _ms_id)


@settings(max_examples=60, deadline=None)
@given(rows=st.lists(_span_row, min_size=1, max_size=40),
       res_ms=st.lists(_ms_id, max_size=8),
       thresh=st.sampled_from([0.0, 0.5, 0.6, 1.0]))
def test_coverage_filter_packed_matches_string_oracle(rows, res_ms, thresh):
    df = pd.DataFrame(rows, columns=["traceid", "um", "dm"])
    res = pd.DataFrame({"msname": pd.Series(res_ms, dtype=np.int64)})
    cfg = IngestConfig(min_resource_coverage=thresh)
    kept = filter_by_resource_coverage(df, res, cfg)

    # oracle: identical ids mapped to strings — guaranteed general path
    sdf = df.copy()
    for c in ("traceid", "um", "dm"):
        sdf[c] = "s" + sdf[c].astype(str)
    sres = pd.DataFrame({"msname": "s" + res["msname"].astype(str)})
    oracle = filter_by_resource_coverage(sdf, sres, cfg)

    assert list(kept.index) == list(oracle.index)


def test_coverage_filter_mixed_domain_takes_general_path():
    # int span codes + string resource names must not raise (ADVICE r4):
    # zero overlap between domains -> zero coverage -> all filtered
    df = pd.DataFrame({"traceid": [1, 1], "um": [0, 1], "dm": [1, 2]})
    res = pd.DataFrame({"msname": ["a", "b"]})
    kept = filter_by_resource_coverage(df, res, IngestConfig())
    assert len(kept) == 0


# ---------------------------------------------------------------------------
# 2. ResourceLookup: hashed gather == dict oracle, in and out of bounds
# ---------------------------------------------------------------------------

_bucket = st.integers(0, 3) | st.integers(2**40 - 1, 2**40 + 1) | \
    st.integers(-2**40 - 1, -(2**40 - 1))
_ms_small = st.integers(0, 3) | st.integers(2**22 - 1, 2**22 + 1) | \
    st.just(-1)
_pair = st.tuples(_bucket, _ms_small)


@settings(max_examples=60, deadline=None)
@given(table=st.lists(_pair, min_size=1, max_size=20, unique=True),
       queries=st.lists(_pair, min_size=1, max_size=30))
def test_resource_lookup_matches_dict_oracle(table, queries):
    rng = np.random.default_rng(0)
    feats = rng.normal(size=(len(table), NUM_RESOURCE_FEATURES)).astype(
        np.float32)
    res = pd.DataFrame({
        "timestamp": pd.Series([t for t, _ in table], dtype=np.int64),
        "msname": pd.Series([m for _, m in table], dtype=np.int64),
        **{f"f{i}": feats[:, i] for i in range(NUM_RESOURCE_FEATURES)},
    })
    lut = ResourceLookup(res)
    oracle = {k: feats[i] for i, k in enumerate(table)}

    ts = np.array([t for t, _ in queries], dtype=np.int64)
    ms = np.array([m for _, m in queries], dtype=np.int64)
    x = lut(ts, ms)
    assert x.shape == (len(queries), NUM_RESOURCE_FEATURES + 1)
    for row, key in zip(x, queries):
        if key in oracle:
            np.testing.assert_array_equal(row[:-1], oracle[key])
            assert row[-1] == 0.0
        else:  # missing: zero features + indicator — NEVER another row's
            np.testing.assert_array_equal(
                row[:-1], np.zeros(NUM_RESOURCE_FEATURES, np.float32))
            assert row[-1] == 1.0


def test_resource_lookup_unpacked_table_path():
    # one table key beyond the ms bound forces the MultiIndex path for
    # the WHOLE table; lookups must still be exact
    res = pd.DataFrame({
        "timestamp": pd.Series([5, 7], dtype=np.int64),
        "msname": pd.Series([3, 2**22 + 9], dtype=np.int64),
        **{f"f{i}": np.float32([i + 1, -(i + 1)])
           for i in range(NUM_RESOURCE_FEATURES)},
    })
    lut = ResourceLookup(res)
    assert not lut._packed
    x = lut(np.array([7, 5, 5]), np.array([2**22 + 9, 3, 4]))
    np.testing.assert_array_equal(
        x[0, :-1], -(np.arange(NUM_RESOURCE_FEATURES, dtype=np.float32) + 1))
    np.testing.assert_array_equal(
        x[1, :-1], np.arange(NUM_RESOURCE_FEATURES, dtype=np.float32) + 1)
    assert x[2, -1] == 1.0 and not x[2, :-1].any()


# ---------------------------------------------------------------------------
# 3. runtime-pattern identity: packed tokens == string corpus factorize
# ---------------------------------------------------------------------------

_tok_id = st.integers(0, 5) | st.integers(2**31 - 1, 2**31 + 1)


@settings(max_examples=60, deadline=None)
@given(rows=st.lists(
    st.tuples(st.integers(0, 6), _tok_id, _tok_id, _tok_id),
    min_size=1, max_size=40))
def test_runtime_ids_numeric_matches_string_corpus(rows, ):
    df = pd.DataFrame(rows, columns=["traceid", "um", "dm", "interface"])
    got = _runtime_ids_numeric(df)

    token = (df["um"].astype(str) + "_" + df["dm"].astype(str)
             + "_" + df["interface"].astype(str))
    corpus = token.groupby(df["traceid"]).agg(" ".join)
    codes, _ = pd.factorize(corpus)
    if got is None:
        # fast path declined (packing would overflow) — legitimate only
        # when the dynamic widths truly exceed 62 bits
        bits = [int(df[c].max()).bit_length() + 1
                for c in ("um", "dm", "interface")]
        assert sum(bits) > 62
        return
    assert list(got.index) == list(corpus.index)
    np.testing.assert_array_equal(got.values, codes)


def test_runtime_ids_numeric_declines_negatives():
    df = pd.DataFrame({"traceid": [0], "um": [-1], "dm": [0],
                       "interface": [0]})
    assert _runtime_ids_numeric(df) is None
