"""Model numerics tests.

Oracles:
- a dense numpy re-implementation of TransformerConv attention (explicit
  per-destination softmax loops) checks GraphTransformerLayer;
- torch.nn.BatchNorm1d (CPU) checks MaskedBatchNorm on the valid rows;
- padding invariance: enlarging the pad region of a batch must not change
  any real output (SURVEY.md §4 "Numerics").
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pertgnn_tpu.batching.pack import PackedBatch
from pertgnn_tpu.config import ModelConfig
from pertgnn_tpu.models.layers import GraphTransformerLayer, MaskedBatchNorm
from pertgnn_tpu.models.pert_model import make_model


def numpy_transformer_conv(params, x, edge_feat, senders, receivers, heads):
    """Dense oracle for PyG TransformerConv semantics (model.py:99-104)."""
    def lin(name, v):
        p = params[name]
        out = v @ np.asarray(p["kernel"])
        if "bias" in p:
            out = out + np.asarray(p["bias"])
        return out

    N = x.shape[0]
    HC = params["query"]["kernel"].shape[1]
    C = HC // heads
    q = lin("query", x).reshape(N, heads, C)
    k = lin("key", x).reshape(N, heads, C)
    v = lin("value", x).reshape(N, heads, C)
    e = lin("edge", edge_feat).reshape(len(senders), heads, C)
    out = np.zeros((N, heads, C))
    for i in range(N):
        inc = [j for j, r in enumerate(receivers) if r == i]
        if not inc:
            continue
        for h in range(heads):
            scores = np.array([
                np.dot(q[i, h], k[senders[j], h] + e[j, h]) / np.sqrt(C)
                for j in inc])
            a = np.exp(scores - scores.max())
            a = a / a.sum()
            out[i, h] = sum(
                a[t] * (v[senders[j], h] + e[j, h])
                for t, j in enumerate(inc))
    return out.reshape(N, HC) + lin("skip", x)


@pytest.mark.parametrize("heads", [1, 4])
def test_layer_matches_dense_oracle(heads):
    rng = np.random.default_rng(0)
    N, E, F, FE, H = 7, 12, 5, 6, heads
    x = rng.normal(size=(N, F)).astype(np.float32)
    ef = rng.normal(size=(E, FE)).astype(np.float32)
    senders = rng.integers(0, N, E)
    receivers = rng.integers(0, N - 1, E)  # node N-1 has no incoming edges
    mask = np.ones(E, dtype=bool)

    layer = GraphTransformerLayer(out_channels=8, heads=H)
    params = layer.init(jax.random.PRNGKey(0), x, ef,
                        jnp.array(senders), jnp.array(receivers),
                        jnp.array(mask))
    got = layer.apply(params, x, ef, jnp.array(senders),
                      jnp.array(receivers), jnp.array(mask))
    want = numpy_transformer_conv(
        jax.tree.map(np.asarray, params["params"]), x, ef, senders,
        receivers, H)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)


def test_attn_dropout_train_vs_eval():
    """attn_dropout perturbs attention weights only in training (per-rng),
    and eval-mode output equals the no-dropout layer exactly (PyG
    TransformerConv semantics)."""
    rng = np.random.default_rng(4)
    N, E = 10, 40
    x = rng.normal(size=(N, 6)).astype(np.float32)
    ef = rng.normal(size=(E, 6)).astype(np.float32)
    snd = jnp.array(rng.integers(0, N, E))
    rcv = jnp.array(rng.integers(0, N, E))
    mask = jnp.ones(E, dtype=bool)

    plain = GraphTransformerLayer(out_channels=8)
    drop = GraphTransformerLayer(out_channels=8, attn_dropout=0.5)
    params = plain.init(jax.random.PRNGKey(0), x, ef, snd, rcv, mask)

    out_eval = drop.apply(params, x, ef, snd, rcv, mask, training=False)
    out_plain = plain.apply(params, x, ef, snd, rcv, mask, training=False)
    np.testing.assert_array_equal(np.asarray(out_eval), np.asarray(out_plain))

    out_t1 = drop.apply(params, x, ef, snd, rcv, mask, training=True,
                        rngs={"dropout": jax.random.PRNGKey(1)})
    out_t2 = drop.apply(params, x, ef, snd, rcv, mask, training=True,
                        rngs={"dropout": jax.random.PRNGKey(2)})
    assert not np.allclose(np.asarray(out_t1), np.asarray(out_t2))
    assert not np.allclose(np.asarray(out_t1), np.asarray(out_plain))


def test_bf16_activations_close_to_f32(preprocessed, small_config):
    """bf16_activations keeps params f32 and runs the forward in bf16:
    predictions must track the f32 path within bf16 tolerance."""
    import dataclasses

    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import ModelConfig
    from pertgnn_tpu.models.pert_model import make_model

    ds = build_dataset(preprocessed, small_config)
    batch = jax.tree.map(jnp.asarray, next(ds.batches("train")))
    m32 = make_model(ModelConfig(hidden_channels=16), ds.num_ms,
                     ds.num_entries, ds.num_interfaces, ds.num_rpctypes)
    m16 = make_model(ModelConfig(hidden_channels=16, bf16_activations=True),
                     ds.num_ms, ds.num_entries, ds.num_interfaces,
                     ds.num_rpctypes)
    variables = m32.init(jax.random.PRNGKey(0), batch, training=False)
    # params stay f32 regardless of activation dtype
    assert all(l.dtype == jnp.float32
               for l in jax.tree.leaves(variables["params"]))
    g32, _ = m32.apply(variables, batch, training=False)
    g16, _ = m16.apply(variables, batch, training=False)
    assert g16.dtype == jnp.float32  # heads cast back for the loss
    np.testing.assert_allclose(np.asarray(g16), np.asarray(g32),
                               rtol=0.05, atol=0.05)


def test_isolated_node_gets_skip_only():
    """A destination with no incoming edges = skip projection only (PyG:
    never appears in the scatter)."""
    x = np.ones((3, 4), dtype=np.float32)
    ef = np.ones((1, 4), dtype=np.float32)
    senders, receivers = jnp.array([0]), jnp.array([1])
    mask = jnp.array([True])
    layer = GraphTransformerLayer(out_channels=4)
    params = layer.init(jax.random.PRNGKey(1), x, ef, senders, receivers, mask)
    out = layer.apply(params, x, ef, senders, receivers, mask)
    p = jax.tree.map(np.asarray, params["params"])
    skip = x @ p["skip"]["kernel"] + p["skip"]["bias"]
    np.testing.assert_allclose(np.asarray(out)[2], skip[2], rtol=1e-5)


class TestMaskedBatchNorm:
    def test_matches_torch_on_valid_rows(self):
        import torch

        rng = np.random.default_rng(3)
        x = rng.normal(2.0, 3.0, size=(10, 6)).astype(np.float32)
        mask = np.array([True] * 7 + [False] * 3)

        bn = MaskedBatchNorm()
        vars_ = bn.init(jax.random.PRNGKey(0), x, jnp.array(mask),
                        training=True)
        out, updates = bn.apply(vars_, x, jnp.array(mask), training=True,
                                mutable=["batch_stats"])

        tbn = torch.nn.BatchNorm1d(6)
        tout = tbn(torch.tensor(x[:7])).detach().numpy()
        np.testing.assert_allclose(np.asarray(out)[:7], tout, rtol=1e-3,
                                   atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(updates["batch_stats"]["mean"]),
            tbn.running_mean.numpy(), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(updates["batch_stats"]["var"]),
            tbn.running_var.numpy(), rtol=1e-4, atol=1e-5)

    def test_eval_uses_running_stats(self):
        x = np.ones((4, 2), dtype=np.float32)
        mask = jnp.ones(4, dtype=bool)
        bn = MaskedBatchNorm()
        vars_ = bn.init(jax.random.PRNGKey(0), x, mask, training=True)
        out = bn.apply(vars_, x, mask, training=False)
        # fresh stats: mean 0, var 1 -> output ~ x
        np.testing.assert_allclose(np.asarray(out), x, rtol=1e-4)


def _tiny_batch(num_graphs=3, n=20, e=24, f=9, pad_nodes=0, pad_edges=0,
                seed=0):
    """A hand-rolled PackedBatch with `pad_*` extra padding lanes."""
    rng = np.random.default_rng(seed)
    G = num_graphs + 1
    N, E = n + pad_nodes, e + pad_edges
    node_graph = np.full(N, G - 1, dtype=np.int32)
    node_graph[:n] = np.sort(rng.integers(0, num_graphs, n))
    node_mask = np.zeros(N, dtype=bool)
    node_mask[:n] = True
    senders = np.zeros(E, dtype=np.int32)
    receivers = np.zeros(E, dtype=np.int32)
    # real edges stay within a graph
    for j in range(e):
        g = rng.integers(0, num_graphs)
        nodes = np.where((node_graph == g) & node_mask)[0]
        senders[j], receivers[j] = rng.choice(nodes, 2)
    # honor the PackedBatch contract: real edges receiver-sorted, pads tail
    order = np.argsort(receivers[:e], kind="stable")
    senders[:e], receivers[:e] = senders[:e][order], receivers[:e][order]
    edge_mask = np.zeros(E, dtype=bool)
    edge_mask[:e] = True
    pattern_size = np.ones(N, dtype=np.float32)
    counts = np.bincount(node_graph[:n], minlength=G)
    pattern_size[:n] = counts[node_graph[:n]]
    return PackedBatch(
        x=np.where(node_mask[:, None], rng.normal(size=(N, f)), 0.0
                   ).astype(np.float32),
        ms_id=np.where(node_mask, rng.integers(0, 5, N), 0).astype(np.int32),
        node_depth=np.zeros(N, dtype=np.float32),
        node_graph=node_graph,
        node_mask=node_mask,
        pattern_prob=np.where(node_mask, 1.0, 0.0).astype(np.float32),
        pattern_size=pattern_size,
        senders=senders,
        receivers=receivers,
        edge_iface=np.where(edge_mask, rng.integers(0, 4, E), 0
                            ).astype(np.int32),
        edge_rpctype=np.where(edge_mask, rng.integers(0, 3, E), 0
                              ).astype(np.int32),
        edge_duration=np.where(edge_mask, rng.exponential(50.0, E),
                               0.0).astype(np.float32),
        edge_mask=edge_mask,
        entry_id=np.arange(G, dtype=np.int32) % 4,
        y=rng.uniform(1, 10, G).astype(np.float32),
        graph_mask=np.array([True] * num_graphs + [False]),
    )


def _pad_batch(b: PackedBatch, extra_nodes: int, extra_edges: int,
               extra_graphs: int = 0) -> PackedBatch:
    """Append padding lanes to an existing batch."""
    G = b.num_graphs + extra_graphs

    def pad(a, k, fill=0):
        return np.concatenate([a, np.full((k,) + a.shape[1:], fill,
                                          dtype=a.dtype)])

    return PackedBatch(
        x=pad(b.x, extra_nodes),
        ms_id=pad(b.ms_id, extra_nodes),
        node_depth=pad(b.node_depth, extra_nodes),
        node_graph=np.concatenate([
            np.where(b.node_mask, b.node_graph, G - 1),
            np.full(extra_nodes, G - 1, dtype=np.int32)]),
        node_mask=pad(b.node_mask, extra_nodes),
        pattern_prob=pad(b.pattern_prob, extra_nodes),
        pattern_size=pad(b.pattern_size, extra_nodes, 1),
        senders=pad(b.senders, extra_edges),
        receivers=pad(b.receivers, extra_edges),
        edge_iface=pad(b.edge_iface, extra_edges),
        edge_rpctype=pad(b.edge_rpctype, extra_edges),
        edge_duration=pad(b.edge_duration, extra_edges),
        edge_mask=pad(b.edge_mask, extra_edges),
        entry_id=pad(b.entry_id, extra_graphs),
        y=pad(b.y, extra_graphs),
        graph_mask=pad(b.graph_mask, extra_graphs),
    )


class TestPaddingInvariance:
    """Padding must be unobservable for EVERY attention_impl and both
    activation tiers (f32, and the bf16 the quantized serve dtypes run
    — int8 is a serve-side weight transform feeding the same bf16
    model, covered end-to-end by test_serve's matrix). The static twin
    is graftaudit's padding-taint pass (docs/LINTS.md); plain "pallas"
    rides the `slow` marker like the parity grid above."""

    IMPLS = (pytest.param("pallas", marks=pytest.mark.slow),
             "segment", "pallas_fused", "blocked_dense")

    @pytest.mark.parametrize("tier", ["f32", "bf16"])
    @pytest.mark.parametrize("impl", IMPLS)
    @pytest.mark.parametrize("training", [False, True])
    def test_model_output_unchanged_by_padding(self, training, impl,
                                               tier):
        if training and tier == "bf16":
            pytest.skip("bf16 activations are a serve tier; training "
                        "runs f32")
        cfg = ModelConfig(hidden_channels=16, num_layers=3,
                          attention_impl=impl,
                          bf16_activations=(tier == "bf16"))
        model = make_model(cfg, num_ms=5, num_entries=4, num_interfaces=4,
                           num_rpctypes=3)
        tol = (dict(rtol=3e-2, atol=3e-2) if tier == "bf16"
               else dict(rtol=2e-4, atol=1e-5))
        b = _tiny_batch()
        big = _pad_batch(b, extra_nodes=33, extra_edges=17, extra_graphs=2)
        jb = jax.tree.map(jnp.asarray, b)
        jbig = jax.tree.map(jnp.asarray, big)
        vars_ = model.init(jax.random.PRNGKey(0), jb, training=False)

        kwargs = dict(training=training)
        if training:
            kwargs["mutable"] = ["batch_stats"]
        out_small = model.apply(vars_, jb, **kwargs)
        out_big = model.apply(vars_, jbig, **kwargs)
        gp_s, lp_s = out_small[0] if training else out_small
        gp_b, lp_b = out_big[0] if training else out_big

        n_real_graphs = int(b.graph_mask.sum())
        np.testing.assert_allclose(
            np.asarray(gp_b)[:n_real_graphs],
            np.asarray(gp_s)[:n_real_graphs], **tol)
        np.testing.assert_allclose(
            np.asarray(lp_b)[b.node_mask.nonzero()[0]],
            np.asarray(lp_s)[b.node_mask.nonzero()[0]], **tol)
        if training:
            # running stats must also be padding-invariant
            s_small = out_small[1]["batch_stats"]
            s_big = out_big[1]["batch_stats"]
            jax.tree.map(
                lambda a, c: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(c), **tol),
                s_small, s_big)


def test_model_reference_stack_arithmetic():
    """num_layers=1 still builds 2 convs + 1 bn (model.py:24-52)."""
    cfg = ModelConfig(hidden_channels=8, num_layers=1)
    model = make_model(cfg, 5, 4, 4, 3)
    b = jax.tree.map(jnp.asarray, _tiny_batch())
    vars_ = model.init(jax.random.PRNGKey(0), b, training=False)
    names = set(vars_["params"].keys())
    assert {"conv_0", "conv_1"} <= names
    assert "conv_2" not in names
    assert "bn_0" in names and "bn_1" not in names
    gp, lp = model.apply(vars_, b, training=False)
    assert gp.shape == (b.entry_id.shape[0],)
    assert lp.shape == (b.x.shape[0],)
    assert np.isfinite(np.asarray(gp)).all()


class TestAttentionImplGrid:
    """The kernel variants (ISSUE 6) are IMPLEMENTATIONS of one layer:
    forward, gradient, and full fit() trajectories must match the
    segment reference on CPU (Pallas variants in interpret mode)."""

    # Plain "pallas" parity is already tier-1 via the kernel-level tests
    # and test_model_forward_with_pallas_flag; its interpret-mode grid
    # runs are the slowest, so they ride in the slow lane.
    IMPLS = (pytest.param("pallas", marks=pytest.mark.slow),
             "pallas_fused", "blocked_dense")

    @pytest.mark.parametrize("impl", IMPLS)
    def test_model_forward_matches_segment(self, impl):
        b = jax.tree.map(jnp.asarray, _tiny_batch())
        outs = {}
        for which in ("segment", impl):
            cfg = ModelConfig(hidden_channels=16, num_layers=2,
                              attention_impl=which)
            model = make_model(cfg, num_ms=5, num_entries=4,
                               num_interfaces=4, num_rpctypes=3)
            vars_ = model.init(jax.random.PRNGKey(0), b, training=False)
            outs[which] = model.apply(vars_, b, training=False)
        np.testing.assert_allclose(np.asarray(outs["segment"][0]),
                                   np.asarray(outs[impl][0]),
                                   rtol=1e-4, atol=1e-5)

    @pytest.fixture(scope="class")
    def grid_ds_and_segment_hist(self, preprocessed):
        from pertgnn_tpu.batching import build_dataset
        from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                        TrainConfig)
        from pertgnn_tpu.train.loop import fit

        base = Config(
            ingest=IngestConfig(min_traces_per_entry=10),
            data=DataConfig(max_traces=96, batch_size=8),
            model=ModelConfig(hidden_channels=8, num_layers=2),
            train=TrainConfig(lr=1e-2, epochs=2, label_scale=1000.0),
        )
        ds = build_dataset(preprocessed, base)
        _, hist = fit(ds, base)
        return base, ds, hist

    @pytest.mark.parametrize("impl", IMPLS)
    def test_fit_grid_twin(self, grid_ds_and_segment_hist, impl):
        """The grid twin: two epochs of fit() under each attention_impl
        land on the segment trajectory within float tolerance — training
        numerics, not just a single forward. (pallas_fused's BN
        statistics use the E[y²]−E[y]² formulation, so equality is
        float-tolerant, not bitwise.)"""
        import dataclasses

        from pertgnn_tpu.train.loop import fit

        base, ds, hist_seg = grid_ds_and_segment_hist
        cfg = base.replace(model=dataclasses.replace(
            base.model, attention_impl=impl))
        _, hist_var = fit(ds, cfg)
        assert len(hist_var) == len(hist_seg)
        for rs, rv in zip(hist_seg, hist_var):
            for k in ("train_qloss", "train_mae", "valid_mae",
                      "test_mae"):
                np.testing.assert_allclose(
                    rv[k], rs[k], rtol=5e-3,
                    err_msg=f"{impl}: history field {k}")

    def test_blocked_dense_over_cells_falls_back_loudly(self, caplog):
        """blocked_dense above max_cells must take the segment path AND
        leave a trace (log + model.kernel_fallback counter) — identical
        output, never a silent formulation switch."""
        import logging

        b = jax.tree.map(jnp.asarray, _tiny_batch())
        out = {}
        for cells in (1 << 22, 1):  # admissible, then inadmissible
            cfg = ModelConfig(hidden_channels=16, num_layers=2,
                              attention_impl="blocked_dense",
                              blocked_dense_max_cells=cells)
            model = make_model(cfg, num_ms=5, num_entries=4,
                               num_interfaces=4, num_rpctypes=3)
            vars_ = model.init(jax.random.PRNGKey(0), b, training=False)
            with caplog.at_level(logging.WARNING,
                                 logger="pertgnn_tpu.models.layers"):
                out[cells] = np.asarray(model.apply(
                    vars_, b, training=False)[0])
        np.testing.assert_allclose(out[1], out[1 << 22],
                                   rtol=1e-5, atol=1e-6)
        assert any("fell back to the segment path" in r.message
                   for r in caplog.records)


def test_nonnegative_option():
    cfg = ModelConfig(hidden_channels=8, nonnegative_pred=True)
    model = make_model(cfg, 5, 4, 4, 3)
    b = jax.tree.map(jnp.asarray, _tiny_batch(seed=5))
    vars_ = model.init(jax.random.PRNGKey(2), b, training=False)
    gp, _ = model.apply(vars_, b, training=False)
    assert (np.asarray(gp) >= 0).all()


def test_edge_durations_option():
    """use_edge_durations feeds |rt| (log1p) as an extra edge feature —
    output must change vs. the flag off, and padding stays invisible."""
    b = jax.tree.map(jnp.asarray, _tiny_batch())
    outs = {}
    for flag in (False, True):
        cfg = ModelConfig(hidden_channels=16, num_layers=2,
                          use_edge_durations=flag)
        model = make_model(cfg, num_ms=5, num_entries=4, num_interfaces=4,
                           num_rpctypes=3)
        vars_ = model.init(jax.random.PRNGKey(0), b, training=False)
        outs[flag] = model.apply(vars_, b, training=False)[0]
    assert not np.allclose(np.asarray(outs[False]), np.asarray(outs[True]))

    cfg = ModelConfig(hidden_channels=16, num_layers=2,
                      use_edge_durations=True)
    model = make_model(cfg, num_ms=5, num_entries=4, num_interfaces=4,
                       num_rpctypes=3)
    small = _tiny_batch()
    big = _pad_batch(small, extra_nodes=9, extra_edges=11)
    vars_ = model.init(jax.random.PRNGKey(0),
                       jax.tree.map(jnp.asarray, small), training=False)
    gp_s = model.apply(vars_, jax.tree.map(jnp.asarray, small),
                       training=False)[0]
    gp_b = model.apply(vars_, jax.tree.map(jnp.asarray, big),
                       training=False)[0]
    n_real = int(small.graph_mask.sum())
    np.testing.assert_allclose(np.asarray(gp_b)[:n_real],
                               np.asarray(gp_s)[:n_real],
                               rtol=2e-4, atol=1e-5)


def test_torch_reference_stack_weight_transfer_parity(preprocessed,
                                                      small_config):
    """The measured baseline (bench.make_torch_reference) must compute the
    SAME function as our flax model: copy one set of weights into both and
    compare eval-mode global predictions on a real packed batch. Pins the
    baseline's architectural faithfulness (pad edges dropped, BN masked —
    the reference's ragged PyG batches have no padding at all,
    pert_gnn.py:201-209)."""
    import torch

    from pertgnn_tpu.batching import build_dataset
    from bench import make_torch_reference, transfer_params_to_torch

    ds = build_dataset(preprocessed, small_config)
    cfg = small_config
    batch = next(ds.batches("train"))
    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    jb = jax.tree.map(jnp.asarray, batch)
    variables = model.init(jax.random.PRNGKey(3), jb, training=False)
    ours = np.asarray(model.apply(variables, jb, training=False)[0])

    tmodel, _, _, to_torch = make_torch_reference(ds, cfg, batch.x.shape[1])
    transfer_params_to_torch(tmodel, variables["params"],
                             max(2, cfg.model.num_layers))

    tmodel.eval()
    with torch.no_grad():
        theirs = tmodel(to_torch(batch)).numpy()
    mask = batch.graph_mask
    np.testing.assert_allclose(ours[mask], theirs[mask],
                               rtol=2e-4, atol=2e-4)
