"""graftmemo (ISSUE 20): the content-keyed prediction cache and the
edit canonicalizer it keys on.

Layered cheapest-first, like the sibling suites:

1. pure canon algebra — normal-form examples for every transformation
   (drop-run set equivalence, commuting-sub sort, no-op dedup), the
   pass-through fragment (over-cap, unknown op), idempotency, and the
   cache-key wrapper;
2. the canon ORACLE under hypothesis: for random edit scripts over a
   real built mixture, ``apply_whatif(m, edits)`` and
   ``apply_whatif(m, canonical_edits(edits))`` are array-identical or
   both refuse — the soundness property the memo's key dedup rests on;
3. memo mechanics — miss/insert/hit round-trip through the wire codec,
   keying sensitivity per key component, per-generation-component
   invalidation, LRU byte bound under churn, oversize/non-pred/error
   refusals;
4. the rollout-flip races, BOTH orders each, under the scripted
   scheduler (testing/schedules.py): flip-vs-in-flight-insert and
   flip-vs-lookup — in every explored order a post-flip lookup can
   never return an old-generation byte (stale reads impossible by
   construction, the ISSUE 20 acceptance property);
5. loadgen vector result slots (the lifted PR-15 refusal): (n, T)
   preds under ``vector_width``, row-wise served mask, admission
   errors recorded without losing futures;
6. counterfactual search through a router-shaped fake submit —
   canonical dedup, argmin honesty, typed budget refusal vs honest
   truncation, WhatIfRefused pruning.
"""

import dataclasses
import threading
from concurrent.futures import Future

import numpy as np
import pytest

from pertgnn_tpu.batching.mixture import build_mixtures
from pertgnn_tpu.fleet import loadgen, wire
from pertgnn_tpu.fleet.memo import PredictionMemo
from pertgnn_tpu.fleet.search import (
    CounterfactualSearch,
    SearchBudgetExhausted,
    SearchSpec,
)
from pertgnn_tpu.graphs.construct import GraphSpec
from pertgnn_tpu.lens.canon import canonical_edits, canonical_lens_key
from pertgnn_tpu.lens.whatif import MAX_EDITS, apply_whatif
from pertgnn_tpu.serve.errors import Shed, WhatIfRefused
from pertgnn_tpu.telemetry.bus import NoopBus
from pertgnn_tpu.testing import schedules
from pertgnn_tpu.testing.schedules import ScriptedScheduler


# --- 1. the canonical normal form -----------------------------------------


def test_drop_run_is_set_equivalent():
    # both scripts drop original edges {0, 1}; the normal form is the
    # descending original-space emission
    a = canonical_edits([{"op": "drop_edge", "edge": 0},
                         {"op": "drop_edge", "edge": 0}])
    b = canonical_edits([{"op": "drop_edge", "edge": 1},
                         {"op": "drop_edge", "edge": 0}])
    assert a == b == ({"op": "drop_edge", "edge": 1},
                      {"op": "drop_edge", "edge": 0})


def test_commuting_subs_sort_to_one_form():
    e1 = {"op": "sub_node", "node": 2, "ms_id": 5}
    e2 = {"op": "sub_node", "node": 0, "ms_id": 7}
    e3 = {"op": "sub_edge", "edge": 1, "iface": 3}
    assert (canonical_edits([e1, e2, e3])
            == canonical_edits([e3, e2, e1])
            == canonical_edits([e2, e3, e1])
            == (e3, e2, e1))  # sub_edge first, then by index


def test_noop_dedup_respects_intervening_conflicts():
    a = {"op": "sub_node", "node": 0, "ms_id": 4}
    b = {"op": "sub_node", "node": 0, "ms_id": 6}
    # exact repeat of the LAST write to the slot is dropped...
    assert canonical_edits([a, dict(a)]) == (a,)
    # ...but a repeat separated by a conflicting write is LOAD-BEARING
    # (last-write-wins) and must survive, in order
    assert canonical_edits([a, b, dict(a)]) == (a, b, a)


def test_runs_do_not_cross_a_drop_node_barrier():
    # edge indices after a drop_node are not translatable without the
    # mixture (incident-edge removal) — the segments stay in sequence
    s = [{"op": "drop_edge", "edge": 2},
         {"op": "drop_node", "node": 1},
         {"op": "drop_edge", "edge": 0}]
    assert canonical_edits(s) == tuple(s)


def test_unprovable_fragments_pass_through_unchanged():
    for raw in (
            [{"op": "warp", "edge": 1}],               # unknown op
            [{"op": "drop_edge", "edge": -1}],         # negative index
            [{"op": "drop_edge", "edge": "x"}],        # non-int index
            [{"op": "sub_node", "node": 1}],           # missing ms_id
            [{"op": "sub_edge", "edge": 1}],           # neither field
            ["drop_edge"],                             # not a dict
    ):
        assert canonical_edits(raw) == tuple(raw)
    over = [{"op": "drop_edge", "edge": 0}] * (MAX_EDITS + 1)
    # shrinking an over-cap script under the cap would turn a refusal
    # into an answer — it must pass through untouched
    assert canonical_edits(over) == tuple(over)


def test_canonical_edits_is_idempotent():
    scripts = [
        [{"op": "drop_edge", "edge": 1}, {"op": "drop_edge", "edge": 0}],
        [{"op": "sub_node", "node": 2, "ms_id": 5},
         {"op": "sub_edge", "edge": 0, "rpctype": 1},
         {"op": "sub_node", "node": 2, "ms_id": 5}],
        [{"op": "warp"}],
    ]
    for s in scripts:
        once = canonical_edits(s)
        assert canonical_edits(once) == once


def test_canonical_lens_key_shapes():
    assert canonical_lens_key(None) is None
    assert canonical_lens_key({}) is None
    base = {"edits": [{"op": "drop_edge", "edge": 0},
                      {"op": "drop_edge", "edge": 0}]}
    same = {"edits": [{"op": "drop_edge", "edge": 1},
                      {"op": "drop_edge", "edge": 0}]}
    other = {"edits": [{"op": "drop_edge", "edge": 2},
                       {"op": "drop_edge", "edge": 0}]}
    assert canonical_lens_key(base) == canonical_lens_key(same)
    assert canonical_lens_key(base) != canonical_lens_key(other)
    # attribution k is part of the key; keys are hashable
    assert canonical_lens_key({"k": 3}) != canonical_lens_key({"k": 4})
    assert hash(canonical_lens_key(base)) is not None


# --- 2. the canon oracle --------------------------------------------------


def _spec(nn, edges, ms, depth=None):
    s = np.array([e[0] for e in edges], np.int32)
    r = np.array([e[1] for e in edges], np.int32)
    ea = np.array([[e[2], e[3]] for e in edges],
                  np.int32).reshape(-1, 2)
    return GraphSpec(
        senders=s, receivers=r, edge_attr=ea,
        ms_id=np.array(ms, np.int32),
        node_depth=np.asarray(depth if depth is not None
                              else np.zeros(nn), np.float32),
        num_nodes=nn, edge_durations=None)


@pytest.fixture()
def oracle_mixture():
    """Two patterns (a 3-node chain, a 2-node pair) built through the
    real mixture builder — 5 nodes, 3 edges."""
    g0 = _spec(3, [(0, 1, 5, 0), (1, 2, 6, 1)], [10, 11, 10],
               [0, .5, 1])
    g1 = _spec(2, [(0, 1, 7, 0)], [12, 10], [0, 1])
    e2r = {0: (np.array([0, 1]), np.array([0.7, 0.3], np.float32))}
    return build_mixtures({0: g0, 1: g1}, e2r)[0]


def _mixtures_equal(a, b) -> None:
    for f in dataclasses.fields(a):
        assert np.array_equal(getattr(a, f.name), getattr(b, f.name)), \
            f.name


def _apply(mix, edits):
    """(outcome kind, payload) — refusals compare by message so the
    oracle also pins that canon never CHANGES a refusal."""
    try:
        return "ok", apply_whatif(mix, edits, num_ms=13,
                                  num_interfaces=10, num_rpctypes=5)
    except WhatIfRefused as exc:
        return "refused", str(exc)


def test_canon_matches_whatif_oracle_under_hypothesis(oracle_mixture):
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    edit = st.one_of(
        st.builds(lambda i: {"op": "drop_edge", "edge": i},
                  st.integers(0, 4)),
        st.builds(lambda i: {"op": "drop_node", "node": i},
                  st.integers(0, 5)),
        st.builds(lambda i, m: {"op": "sub_node", "node": i,
                                "ms_id": m},
                  st.integers(0, 5), st.integers(0, 14)),
        st.builds(lambda i, f: {"op": "sub_edge", "edge": i,
                                "iface": f},
                  st.integers(0, 4), st.integers(0, 9)),
        st.builds(lambda i, r: {"op": "sub_edge", "edge": i,
                                "rpctype": r},
                  st.integers(0, 4), st.integers(0, 4)),
        st.builds(lambda i, f, r: {"op": "sub_edge", "edge": i,
                                   "iface": f, "rpctype": r},
                  st.integers(0, 4), st.integers(0, 9),
                  st.integers(0, 4)))

    @hyp.given(st.lists(edit, max_size=6))
    @hyp.settings(deadline=None, max_examples=150)
    def check(script):
        canon = canonical_edits(script)
        # idempotent normal form
        assert canonical_edits(canon) == canon
        raw_kind, raw_out = _apply(oracle_mixture, script)
        can_kind, can_out = _apply(oracle_mixture, canon)
        assert raw_kind == can_kind, (script, canon, raw_out, can_out)
        if raw_kind == "ok":
            _mixtures_equal(raw_out, can_out)

    check()


def test_canon_matches_whatif_oracle_seeded(oracle_mixture):
    """The same oracle property without hypothesis: 300 seeded random
    scripts (mixed ops, in/out-of-range indices) — always runs, so the
    container without hypothesis still pins soundness."""
    rng = np.random.default_rng(20)

    def rand_edit():
        k = rng.integers(0, 6)
        if k == 0:
            return {"op": "drop_edge", "edge": int(rng.integers(0, 5))}
        if k == 1:
            return {"op": "drop_node", "node": int(rng.integers(0, 6))}
        if k == 2:
            return {"op": "sub_node", "node": int(rng.integers(0, 6)),
                    "ms_id": int(rng.integers(0, 15))}
        if k == 3:
            return {"op": "sub_edge", "edge": int(rng.integers(0, 5)),
                    "iface": int(rng.integers(0, 10))}
        if k == 4:
            return {"op": "sub_edge", "edge": int(rng.integers(0, 5)),
                    "rpctype": int(rng.integers(0, 5))}
        return {"op": "sub_edge", "edge": int(rng.integers(0, 5)),
                "iface": int(rng.integers(0, 10)),
                "rpctype": int(rng.integers(0, 5))}

    for _ in range(300):
        script = [rand_edit() for _ in range(int(rng.integers(0, 7)))]
        canon = canonical_edits(script)
        assert canonical_edits(canon) == canon
        raw_kind, raw_out = _apply(oracle_mixture, script)
        can_kind, can_out = _apply(oracle_mixture, canon)
        assert raw_kind == can_kind, (script, canon, raw_out, can_out)
        if raw_kind == "ok":
            _mixtures_equal(raw_out, can_out)


def test_canon_key_is_order_insensitive_for_commuting_subs(
        oracle_mixture):
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    # distinct-target substitutions commute: every permutation must
    # share ONE memo key AND one oracle outcome
    subs = [{"op": "sub_node", "node": 0, "ms_id": 3},
            {"op": "sub_node", "node": 2, "ms_id": 7},
            {"op": "sub_edge", "edge": 1, "iface": 2},
            {"op": "sub_edge", "edge": 0, "rpctype": 1}]
    base_key = canonical_lens_key({"edits": subs})
    base = _apply(oracle_mixture, subs)

    @hyp.given(st.permutations(subs))
    @hyp.settings(deadline=None, max_examples=24)
    def check(perm):
        assert canonical_lens_key({"edits": perm}) == base_key
        kind, out = _apply(oracle_mixture, perm)
        assert kind == base[0]
        if kind == "ok":
            _mixtures_equal(out, base[1])

    check()


# --- 3. memo mechanics ----------------------------------------------------


def _memo(capacity=1 << 16) -> PredictionMemo:
    m = PredictionMemo(capacity, bus=NoopBus())
    m.set_generation(3, "arena-a", (0.5, 0.99))
    return m


def _frame_bytes(row) -> int:
    return len(wire.encode_response([{**row, "cache_hit": True}]))


def test_miss_insert_hit_roundtrip():
    memo = _memo()
    row0, token, nbytes = memo.lookup(7, 3)
    assert row0 is None and nbytes == 0
    assert token is not None and token.gen_seq == 1
    assert memo.insert(token, {"pred": [0.25, 0.5]})
    row1, tok1, nbytes1 = memo.lookup(7, 3)
    # the hit decodes the stored wire frame: bit-identical pred plus
    # the travelling cache_hit flag, no insert permit
    assert row1 == {"pred": [0.25, 0.5], "cache_hit": True}
    assert tok1 is None
    assert nbytes1 == _frame_bytes({"pred": [0.25, 0.5]})
    s = memo.stats_dict()
    assert (s["hits"], s["misses"], s["inserts"]) == (1, 1, 1)
    assert s["entries"] == 1 and s["bytes"] == nbytes1


def test_keying_sensitivity_per_component():
    memo = _memo()
    lens = {"edits": [{"op": "drop_edge", "edge": 0},
                      {"op": "drop_edge", "edge": 0}]}
    for args in ((7, 3, None), (7, 3, lens)):
        _r, tok, _n = memo.lookup(*args)
        assert memo.insert(tok, {"pred": float(hash(str(args)) % 97)})
    # every single-component change misses
    assert memo.lookup(8, 3)[0] is None          # entry
    assert memo.lookup(7, 4)[0] is None          # ts bucket
    assert memo.lookup(7, 3, {"edits": [
        {"op": "drop_edge", "edge": 2},
        {"op": "drop_edge", "edge": 0}]})[0] is None   # different edits
    assert memo.lookup(7, 3, {"k": 2})[0] is None      # attribution k
    # the plain and the lens rows are distinct entries...
    assert memo.lookup(7, 3)[0] is not None
    # ...and an EQUIVALENT edit script (same drop set, other order)
    # hits the same entry
    hit, _t, _n = memo.lookup(7, 3, {"edits": [
        {"op": "drop_edge", "edge": 1},
        {"op": "drop_edge", "edge": 0}]})
    assert hit is not None and hit["cache_hit"] is True


@pytest.mark.parametrize("flip", [
    dict(checkpoint_epoch=4, arena_fingerprint="arena-a",
         taus=(0.5, 0.99)),                        # epoch moved
    dict(checkpoint_epoch=3, arena_fingerprint="arena-b",
         taus=(0.5, 0.99)),                        # arena moved
    dict(checkpoint_epoch=3, arena_fingerprint="arena-a",
         taus=(0.5, 0.9, 0.99)),                   # head layout moved
])
def test_every_generation_component_invalidates(flip):
    memo = _memo()
    _r, token, _n = memo.lookup(7, 3)
    assert memo.insert(token, {"pred": 1.5})
    _r, stale_token, _n = memo.lookup(9, 9)   # miss under gen 1
    memo.set_generation(**flip)
    # the store is empty the instant the generation moves...
    assert memo.lookup(7, 3)[0] is None
    assert memo.retired == 1
    # ...and the in-flight permit from gen 1 is refused
    assert not memo.insert(stale_token, {"pred": 2.5})
    assert memo.stale_inserts == 1
    assert memo.stats_dict()["entries"] == 0


def test_uncacheable_rows_and_tokens_are_refused():
    memo = _memo()
    _r, token, _n = memo.lookup(1, 1)
    assert not memo.insert(None, {"pred": 1.0})            # no permit
    assert not memo.insert(token, {"error": "Shed",
                                   "message": "x"})        # error row
    assert not memo.insert(token, {"rows": 3})             # not a pred
    assert memo.stats_dict()["entries"] == 0


def test_no_generation_means_no_permits_and_no_storage():
    memo = PredictionMemo(1 << 16, bus=NoopBus())
    row, token, _n = memo.lookup(1, 1)
    assert row is None and token is None
    with pytest.raises(ValueError):
        PredictionMemo(0)


def test_oversize_frame_is_refused_not_thrashed():
    row = {"pred": [float(i) for i in range(64)]}
    memo = PredictionMemo(_frame_bytes(row) - 1, bus=NoopBus())
    memo.set_generation(1, "a", (0.5,))
    _r, token, _n = memo.lookup(1, 1)
    assert not memo.insert(token, row)
    assert memo.oversize == 1 and memo.stats_dict()["entries"] == 0


def test_lru_byte_bound_under_churn():
    row = {"pred": [0.25, 0.5, 0.75]}
    per = _frame_bytes(row)
    memo = PredictionMemo(3 * per, bus=NoopBus())
    memo.set_generation(1, "a", (0.5,))
    for eid in range(8):
        _r, tok, _n = memo.lookup(eid, 0)
        assert memo.insert(tok, row)
        assert memo.stats_dict()["bytes"] <= memo.capacity_bytes
        # keep entry 0 hot so recency, not insertion order, decides
        if eid >= 1:
            memo.lookup(0, 0)
    s = memo.stats_dict()
    assert s["entries"] == 3 and s["evictions"] == 5
    # the hot entry survived the churn; the cold middle did not
    assert memo.lookup(0, 0)[0] is not None
    assert memo.lookup(7, 0)[0] is not None
    assert memo.lookup(3, 0)[0] is None


def test_retire_generation_empties_and_disables():
    memo = _memo()
    _r, tok, _n = memo.lookup(5, 5)
    assert memo.insert(tok, {"pred": 2.0})
    assert memo.retire_generation(reason="rollout") == 1
    row, token, _n = memo.lookup(5, 5)
    assert row is None and token is None
    assert memo.retired == 1


# --- 4. the rollout-flip races, both orders each --------------------------


def _run_scripted(script, *thunks):
    sched = ScriptedScheduler(list(script), timeout_s=15.0)
    with sched:
        ts = [threading.Thread(target=t, name=f"memo-race-{i}")
              for i, t in enumerate(thunks)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=15.0)
    assert sched.finished(), (sched.trace, sched.script)
    return sched


def _flip_vs_insert_trial(flip_first: bool):
    memo = PredictionMemo(1 << 16, bus=NoopBus())
    memo.set_generation(1, "a", (0.5,))
    _r, token, _n = memo.lookup(7, 3)
    assert token is not None
    out: dict = {}

    def insert():
        out["stored"] = memo.insert(token, {"pred": 1.5})
        schedules.sync_point("test.insert.done")

    def flip():
        out["retired"] = memo.retire_generation(reason="rollout")
        schedules.sync_point("test.flip.done")

    script = (["fleet.memo.flip", "test.flip.done", "fleet.memo.insert"]
              if flip_first else
              ["fleet.memo.insert", "test.insert.done",
               "fleet.memo.flip"])
    _run_scripted(script, insert, flip)
    return memo, out


def test_flip_before_inflight_insert_refuses_the_stale_value():
    memo, out = _flip_vs_insert_trial(flip_first=True)
    assert out["stored"] is False
    assert out["retired"] == 0          # nothing was stored yet
    assert memo.stale_inserts == 1
    assert memo.stats_dict()["entries"] == 0
    # post-flip: no hit, no permit — the old byte is unreachable
    row, token, _n = memo.lookup(7, 3)
    assert row is None and token is None


def test_inflight_insert_before_flip_is_retired_exactly_once():
    memo, out = _flip_vs_insert_trial(flip_first=False)
    assert out["stored"] is True
    assert out["retired"] == 1          # the one stored entry, once
    assert memo.retired == 1 and memo.stale_inserts == 0
    assert memo.stats_dict()["entries"] == 0
    row, token, _n = memo.lookup(7, 3)
    assert row is None and token is None


def _flip_vs_lookup_trial(flip_first: bool):
    memo = PredictionMemo(1 << 16, bus=NoopBus())
    memo.set_generation(1, "a", (0.5,))
    _r, tok, _n = memo.lookup(7, 3)
    assert memo.insert(tok, {"pred": 1.5})
    out: dict = {}

    def lookup():
        out["row"], out["token"], _ = memo.lookup(7, 3)
        schedules.sync_point("test.lookup.done")

    def flip():
        memo.retire_generation(reason="rollout")
        schedules.sync_point("test.flip.done")

    script = (["fleet.memo.flip", "test.flip.done",
               "fleet.memo.lookup"]
              if flip_first else
              ["fleet.memo.lookup", "test.lookup.done",
               "fleet.memo.flip"])
    _run_scripted(script, lookup, flip)
    return memo, out


def test_flip_before_lookup_serves_nothing():
    memo, out = _flip_vs_lookup_trial(flip_first=True)
    # after the flip there is no generation: no hit AND no permit
    assert out["row"] is None and out["token"] is None
    assert memo.stats_dict()["entries"] == 0


def test_lookup_before_flip_serves_the_then_current_value():
    memo, out = _flip_vs_lookup_trial(flip_first=False)
    # the lookup COMPLETED before the flip — the fleet was still
    # uniformly on the old version, so the answer was current
    assert out["row"] == {"pred": 1.5, "cache_hit": True}
    # and the flip still emptied the store afterwards
    assert memo.stats_dict()["entries"] == 0
    assert memo.lookup(7, 3)[0] is None


# --- 5. loadgen vector result slots ---------------------------------------


def _tiny_schedule(n_entries=4):
    spec = loadgen.LoadSpec(duration_s=0.2, base_rps=200.0,
                            zipf_s=0.0, seed=1)
    entries = np.arange(n_entries, dtype=np.int64)
    buckets = np.zeros(n_entries, dtype=np.int64)
    return loadgen.generate_schedule(spec, entries, buckets)


def test_replay_vector_slots_round_trip():
    schedule = _tiny_schedule()

    def submit(eid, tsb, slo=None):
        fut: Future = Future()
        fut.set_result([0.25, 0.5, 0.75])
        return fut

    result = loadgen.replay(submit, schedule, bus=NoopBus(),
                            vector_width=3)
    assert result.preds.shape == (len(schedule), 3)
    assert result.served_mask().all()
    assert result.served_mask().shape == (len(schedule),)
    assert result.lost_futures() == 0
    assert np.array_equal(result.preds[0], [0.25, 0.5, 0.75])


def test_replay_vector_slots_record_errors_without_losing_futures():
    schedule = _tiny_schedule()
    calls = [0]

    def submit(eid, tsb, slo=None):
        calls[0] += 1
        if calls[0] % 2 == 0:
            raise Shed("every other arrival shed", slo=slo)
        fut: Future = Future()
        fut.set_result([1.0, 2.0])
        return fut

    result = loadgen.replay(submit, schedule, bus=NoopBus(),
                            vector_width=2)
    served = result.served_mask()
    assert served.sum() == (len(schedule) + 1) // 2
    assert result.error_counts() == {"Shed": len(schedule) // 2}
    # a shed row is all-NaN across its tau columns, and NOT lost
    assert result.lost_futures() == 0
    assert np.isnan(result.preds[~served]).all()


# ---------------------------------------------------------------------------
# 6. counterfactual search over a fake router front door
# ---------------------------------------------------------------------------


def _search_submit(objective_by_key, *, refuse_keys=()):
    """A router-shaped submit whose answer is a pure function of the
    CANONICAL edit key — the same determinism contract the real engine
    gives the search (bit-identical bits per canonical request)."""

    keys_seen = []

    def submit(eid, tsb, slo=None, lens=None):
        edits = () if lens is None else tuple(lens.edits)
        key = canonical_lens_key({"edits": [dict(e) for e in edits]})
        keys_seen.append(key)
        fut: Future = Future()
        if key in refuse_keys:
            fut.set_exception(WhatIfRefused("pruned by the oracle"))
        else:
            fut.set_result([0.1, objective_by_key(key)])
        return fut

    return submit, keys_seen


def _obj_from_key(key):
    # deterministic, spread-out objectives over the canonical key
    return 50.0 + (hash(key) % 97)


def test_search_budget_too_small_refuses_typed():
    submit, _ = _search_submit(_obj_from_key)
    spec = SearchSpec(entry_id=0, ts_bucket=0, num_nodes=4,
                      num_edges=4, budget=1)
    with pytest.raises(SearchBudgetExhausted):
        CounterfactualSearch(submit, spec, bus=NoopBus()).run()


def test_search_argmin_dedup_and_refusal_pruning():
    refused_key = canonical_lens_key(
        {"edits": [{"op": "drop_edge", "edge": 0}]})
    submit, keys_seen = _search_submit(
        _obj_from_key, refuse_keys={refused_key})
    spec = SearchSpec(entry_id=0, ts_bucket=0, num_nodes=3,
                      num_edges=3, beam_width=2, max_depth=2,
                      budget=96, sub_ms_ids=(1, 2),
                      max_drop_candidates=3, max_sub_nodes=2)
    res = CounterfactualSearch(submit, spec, bus=NoopBus()).run()
    # dedup: every submitted candidate had a DISTINCT canonical key
    assert len(keys_seen) == len(set(keys_seen))
    # the reported best is the argmin over everything evaluated
    assert res.best_objective == min(o for _e, o in res.evaluated)
    assert res.best_objective <= res.baseline
    # the refused candidate was pruned, counted, and did not crash
    assert res.refused == 1
    assert refused_key not in {
        canonical_lens_key({"edits": [dict(e) for e in edits]})
        for edits, _o in res.evaluated}
    assert not res.budget_exhausted
    assert res.requests <= spec.budget


def test_search_truncates_honestly_when_budget_runs_dry():
    submit, _ = _search_submit(_obj_from_key)
    # budget covers the baseline plus a couple of candidates only;
    # the first round alone proposes more than that
    spec = SearchSpec(entry_id=0, ts_bucket=0, num_nodes=4,
                      num_edges=8, beam_width=4, max_depth=3,
                      budget=4, sub_ms_ids=(1,),
                      max_drop_candidates=8, max_sub_nodes=4)
    res = CounterfactualSearch(submit, spec, bus=NoopBus()).run()
    assert res.budget_exhausted
    assert res.requests <= spec.budget
    # the argmin is over what WAS evaluated — still internally honest
    assert res.best_objective == min(o for _e, o in res.evaluated)
