"""tools/graftlint: the multi-pass static-analysis suite, run over the
real repo in tier-1 — the bug classes PRs 3-7 caught by hand (stale AOT
keys, trace hazards, telemetry/doc drift, unlocked shared state,
flag/config drift) must stay mechanically enforced (docs/LINTS.md).

Fixture tests build miniature repos under tmp_path (the driver's
Context only needs the path shape); THE gate is test_repo_lints_clean,
which runs every pass over the live tree inside a wall-clock budget.
"""

import json
import os
import sys
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graftlint import driver, run_repo  # noqa: E402
from tools.graftlint.cli import main as cli_main  # noqa: E402
from tools.graftlint.passes import (aot_keys, flag_config,  # noqa: E402
                                    get_passes, lock_discipline,
                                    telemetry_drift, trace_hazard)

BUDGET_S = 60.0  # the ISSUE-8 acceptance bound; measured ~3-4 s

_REPO_CTX = None


def _repo_ctx():
    """One shared Context over the live tree: the repo-wide tests below
    each need the parsed file set, and re-discovering + re-parsing ~120
    files per test would spend tier-1 wall clock on nothing (the suite
    runs within ~4% of its 870 s budget — every second is rationed)."""
    global _REPO_CTX
    if _REPO_CTX is None:
        _REPO_CTX = driver.Context(REPO)
    return _REPO_CTX


def _mini_repo(tmp_path, files: dict[str, str]) -> str:
    """Materialize {relpath: source} as a repo tree for Context."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _run(tmp_path, files, passes):
    repo = _mini_repo(tmp_path, files)
    return driver.run_passes(repo, passes, baseline_path="")


# --- THE tier-1 gate -----------------------------------------------------


def test_repo_lints_clean():
    """Every pass, whole repo, zero NEW violations, under the budget."""
    t0 = time.perf_counter()
    result = run_repo(REPO)
    elapsed = time.perf_counter() - t0
    assert result.new == [], "\n".join(str(v) for v in result.new)
    assert elapsed < BUDGET_S, (
        f"graftlint took {elapsed:.1f}s — over the {BUDGET_S:.0f}s "
        f"budget the ISSUE-8 acceptance pins")


def test_all_seven_passes_registered():
    names = [m.RULE for m in get_passes(None)]
    assert names == ["excepts", "aot-key-coverage", "trace-hazard",
                     "telemetry-drift", "lock-discipline",
                     "flag-config-drift", "durable-write"]


# --- driver mechanics ----------------------------------------------------


_LOCK_BAD = """
    import threading

    class Q:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            threading.Thread(target=self.work, daemon=True).start()

        def work(self):
            self.count += 1
"""


def test_driver_pragma_suppresses_on_the_line(tmp_path):
    bad = _run(tmp_path, {"pertgnn_tpu/serve/q.py": _LOCK_BAD},
               ["lock-discipline"])
    assert len(bad.new) == 1 and "self.count" in bad.new[0].message
    ok = _run(tmp_path, {"pertgnn_tpu/serve/q.py": _LOCK_BAD.replace(
        "self.count += 1",
        "self.count += 1  # graftlint: allow-lock-discipline")},
        ["lock-discipline"])
    assert ok.new == []


def test_driver_baseline_accepts_known_debt(tmp_path):
    repo = _mini_repo(tmp_path, {"pertgnn_tpu/serve/q.py": _LOCK_BAD})
    first = driver.run_passes(repo, ["lock-discipline"], baseline_path="")
    assert len(first.new) == 1
    baseline = tmp_path / "baseline.json"
    driver.write_baseline(str(baseline), first.new)
    second = driver.run_passes(repo, ["lock-discipline"],
                               baseline_path=str(baseline))
    assert second.new == [] and len(second.baselined) == 1
    # baselines key on (rule, path, key) — a DIFFERENT violation in the
    # same file is still new
    repo2 = _mini_repo(tmp_path, {"pertgnn_tpu/serve/q.py":
                                  _LOCK_BAD.replace("self.count",
                                                    "self.other")})
    third = driver.run_passes(repo2, ["lock-discipline"],
                              baseline_path=str(baseline))
    assert len(third.new) == 1 and "self.other" in third.new[0].message


def test_driver_reports_unparseable_files(tmp_path):
    # under a path at least one pass parses (lock-discipline scope)
    res = _run(tmp_path, {"pertgnn_tpu/serve/bad.py": "def broken(:\n"},
               ["lock-discipline"])
    assert any("unparseable" in v.message for v in res.new)


def test_cli_exit_codes_and_json(tmp_path, capsys):
    repo = _mini_repo(tmp_path, {"pertgnn_tpu/serve/q.py": _LOCK_BAD})
    assert cli_main(["lock-discipline", "--root", repo,
                     "--no-baseline", "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is False and len(doc["violations"]) == 1
    clean = _mini_repo(tmp_path / "clean", {"pertgnn_tpu/ok.py": "x = 1\n"})
    assert cli_main(["--root", clean, "--no-baseline"]) == 0
    assert cli_main(["no-such-pass", "--root", clean]) == 2


def test_cli_write_baseline_roundtrip(tmp_path, capsys):
    repo = _mini_repo(tmp_path, {"pertgnn_tpu/serve/q.py": _LOCK_BAD})
    baseline = str(tmp_path / "b.json")
    assert cli_main(["lock-discipline", "--root", repo,
                     "--baseline", baseline, "--write-baseline"]) == 0
    assert cli_main(["lock-discipline", "--root", repo,
                     "--baseline", baseline]) == 0
    capsys.readouterr()


# --- aot-key-coverage ----------------------------------------------------


_AOT_BASE = """
    import jax
    from pertgnn_tpu import aot

    def make_step(model, cfg):
        def step(state, batch):
            return state * cfg.train.tau{extra}
        return jax.jit(step)

    def build(cfg, sig):
        key, comp = aot.cache_key(
            fn_id="x",
            config={{"train": {{k: getattr(cfg.train, k)
                                for k in ("tau",)}}}},
            args_sig=sig)
        return key
"""


def test_aot_keys_covered_read_is_clean(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/train/loop.py":
                          _AOT_BASE.format(extra="")},
               ["aot-key-coverage"])
    assert res.new == []


def test_aot_keys_uncovered_read_is_flagged(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/train/loop.py":
                          _AOT_BASE.format(
                              extra=" + cfg.train.new_knob")},
               ["aot-key-coverage"])
    assert any("train.new_knob" in v.message for v in res.new)


def test_aot_keys_closure_capture_in_encloser_is_scanned(tmp_path):
    # the factory reads the field OUTSIDE the traced def and closes
    # over it — baked into the program all the same (the engine's
    # label_scale pattern)
    src = _AOT_BASE.format(extra="").replace(
        "def step(state, batch):",
        "knob = cfg.serve.mystery\n        def step(state, batch):")
    res = _run(tmp_path, {"pertgnn_tpu/train/loop.py": src},
               ["aot-key-coverage"])
    assert any("serve.mystery" in v.message for v in res.new)


def test_aot_keys_pragma_suppresses(tmp_path):
    src = _AOT_BASE.format(
        extra=" + cfg.train.new_knob  # graftlint: allow-aot-key-coverage")
    res = _run(tmp_path, {"pertgnn_tpu/train/loop.py": src},
               ["aot-key-coverage"])
    assert res.new == []


def test_aot_keys_real_repo_coverage_includes_known_keys():
    """The live tree's key surface: the PR-3-review fields must stay
    covered (a regression here is exactly the stale-replay bug)."""
    ctx = _repo_ctx()
    covered = aot_keys.collect_coverage(ctx)
    for dotted in ("model.*", "train.label_scale", "train.tau",
                   "train.seed", "train.scan_chunk",
                   "serve.serve_dtype", "graph_type"):
        assert dotted in covered, f"{dotted} fell out of the AOT keys"


# --- trace-hazard --------------------------------------------------------


_TRACE = """
    import jax
    import numpy as np

    def outer(fn):
        def traced(x):
            {body}
        return jax.jit(traced)
"""


def test_trace_hazard_item_and_np_flagged(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/serve/t.py": _TRACE.format(
        body="return np.asarray(x) + x.sum().item()")},
        ["trace-hazard"])
    kinds = {v.message.split(" ", 1)[0] for v in res.new}
    assert kinds == {"H1", "H2"}


def test_trace_hazard_static_partial_kwargs_are_clean(tmp_path):
    # the pallas-kernel pattern: head_dim partial-bound -> host-static,
    # so float(np.sqrt(head_dim)) is deliberate trace-time math
    src = """
        import functools
        import numpy as np
        from jax.experimental import pallas as pl

        def kernel(q_ref, o_ref, *, head_dim):
            o_ref[:] = q_ref[:] * (1.0 / float(np.sqrt(head_dim)))

        def call(q):
            return pl.pallas_call(
                functools.partial(kernel, head_dim=8))(q)
    """
    res = _run(tmp_path, {"pertgnn_tpu/ops/k.py": src}, ["trace-hazard"])
    assert res.new == []


def test_trace_hazard_control_flow_and_print_flagged(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/serve/t.py": _TRACE.format(
        body="\n            ".join([
            "import jax.numpy as jnp",
            "if jnp.any(x):",
            "    print('hit')",
            "return x"]))},
        ["trace-hazard"])
    kinds = {v.message.split(" ", 1)[0] for v in res.new}
    assert kinds == {"H4", "H5"}


def test_trace_hazard_untraced_host_code_is_clean(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/serve/t.py": """
        import numpy as np

        def host_only(x):
            return float(np.asarray(x).sum())
    """}, ["trace-hazard"])
    assert res.new == []


# --- telemetry-drift -----------------------------------------------------


_DOC = """
    # Observability

    | name | kind | notes |
    |------|------|-------|
    | `serve.good` | counter | fine |
    {extra_row}
"""

_EMIT = """
    def f(bus):
        bus.counter("serve.good")
        {extra}
"""


def test_telemetry_in_sync_is_clean(tmp_path):
    res = _run(tmp_path, {
        "docs/OBSERVABILITY.md": _DOC.format(extra_row=""),
        "pertgnn_tpu/a.py": _EMIT.format(extra="")},
        ["telemetry-drift"])
    assert res.new == []


def test_telemetry_undocumented_emission_flagged(tmp_path):
    res = _run(tmp_path, {
        "docs/OBSERVABILITY.md": _DOC.format(extra_row=""),
        "pertgnn_tpu/a.py": _EMIT.format(
            extra='bus.gauge("serve.rogue", 1)')},
        ["telemetry-drift"])
    assert [v.key for v in res.new] == ["undocumented:serve.rogue"]


def test_telemetry_stale_doc_row_flagged(tmp_path):
    res = _run(tmp_path, {
        "docs/OBSERVABILITY.md": _DOC.format(
            extra_row="| `serve.gone` | counter | vanished |"),
        "pertgnn_tpu/a.py": _EMIT.format(extra="")},
        ["telemetry-drift"])
    assert [v.key for v in res.new] == ["stale-doc:serve.gone"]


def test_telemetry_dynamic_name_flagged_and_pragma(tmp_path):
    files = {
        "docs/OBSERVABILITY.md": _DOC.format(extra_row=""),
        "pertgnn_tpu/a.py": _EMIT.format(
            extra='bus.counter("serve." + tag)')}
    res = _run(tmp_path, dict(files), ["telemetry-drift"])
    assert any("dynamic" in v.message for v in res.new)
    files["pertgnn_tpu/a.py"] = _EMIT.format(
        extra='bus.counter("serve." + tag)'
              '  # graftlint: allow-telemetry-drift')
    assert _run(tmp_path, files, ["telemetry-drift"]).new == []


def test_telemetry_variable_name_resolves(tmp_path):
    # the admission fast-path pattern: counter = "serve.good" then
    # bus.counter(counter) — resolved, not dynamic
    res = _run(tmp_path, {
        "docs/OBSERVABILITY.md": _DOC.format(extra_row=""),
        "pertgnn_tpu/a.py": """
            def f(bus, shed):
                counter = None
                if shed:
                    counter = "serve.good"
                if counter:
                    bus.counter(counter)
        """}, ["telemetry-drift"])
    assert res.new == []


def test_telemetry_emit_table_adds_and_drops(tmp_path):
    repo = _mini_repo(tmp_path, {
        "docs/OBSERVABILITY.md": _DOC.format(
            extra_row="| `serve.gone` | counter | vanished |"),
        "pertgnn_tpu/a.py": _EMIT.format(
            extra='bus.gauge("serve.rogue", 1)')})
    ctx = driver.Context(repo)
    content, summary = telemetry_drift.emit_table(ctx)
    assert summary["added"] == ["serve.rogue"]
    assert summary["dropped_rows"] == ["serve.gone"]
    assert "| `serve.rogue` | gauge |" in content
    assert "serve.gone" not in content
    # regenerated doc satisfies the drift check
    (tmp_path / "docs/OBSERVABILITY.md").write_text(content)
    res = driver.run_passes(repo, ["telemetry-drift"], baseline_path="")
    assert res.new == []


def test_telemetry_emit_table_strips_dead_name_from_shared_row(tmp_path):
    # a multi-name row where only one name died: the row survives with
    # the dead token removed, so run() and --emit-table converge
    repo = _mini_repo(tmp_path, {
        "docs/OBSERVABILITY.md": _DOC.format(
            extra_row="| `serve.good` (trace) / `serve.gone` | counter "
                      "| pair |"),
        "pertgnn_tpu/a.py": _EMIT.format(extra="")})
    ctx = driver.Context(repo)
    content, summary = telemetry_drift.emit_table(ctx)
    assert summary["dropped_rows"] == ["serve.gone"]
    assert "serve.gone" not in content
    assert content.count("`serve.good`") == 2  # both rows survive
    (tmp_path / "docs/OBSERVABILITY.md").write_text(content)
    res = driver.run_passes(repo, ["telemetry-drift"], baseline_path="")
    assert res.new == []


def test_telemetry_schema_violating_constant_name_flagged(tmp_path):
    # a constant name the dotted lower_snake schema rejects would be
    # invisible to the contract check — flagged like a dynamic name
    res = _run(tmp_path, {
        "docs/OBSERVABILITY.md": _DOC.format(extra_row=""),
        "pertgnn_tpu/a.py": _EMIT.format(
            extra='bus.counter("serve.Cache-Miss")')},
        ["telemetry-drift"])
    assert [v.key for v in res.new] == ["bad-name:serve.Cache-Miss"]


def test_telemetry_emit_table_is_noop_on_live_tree():
    ctx = _repo_ctx()
    content, summary = telemetry_drift.emit_table(ctx)
    assert summary == {"dropped_rows": [], "added": [], "unplaced": []}
    assert content == ctx.source(telemetry_drift.DOC)


# --- lock-discipline -----------------------------------------------------


def test_lock_locked_suffix_methods_exempt(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/serve/q.py": """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self.work).start()

            def _bump_locked(self):
                self.n += 1

            def work(self):
                with self._lock:
                    self._bump_locked()
    """}, ["lock-discipline"])
    assert res.new == []


def test_lock_condition_wrapping_lock_counts(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/fleet/r.py": """
        import threading

        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self._wake = threading.Condition(self._lock)
                self.n = 0
                threading.Thread(target=self.work).start()

            def work(self):
                with self._wake:
                    self.n += 1
    """}, ["lock-discipline"])
    assert res.new == []


def test_lock_unthreaded_class_is_skipped(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/serve/q.py": """
        import threading

        class NoThreads:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                self.n += 1
    """}, ["lock-discipline"])
    assert res.new == []


def test_lock_locked_suffix_call_outside_lock_flagged(tmp_path):
    # the caller side of the *_locked contract: the suffix's exemption
    # rests on every caller holding the lock — an unlocked call is the
    # data race with extra steps
    res = _run(tmp_path, {"pertgnn_tpu/serve/q.py": """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self.work).start()

            def _bump_locked(self):
                self.n += 1

            def work(self):
                self._bump_locked()
    """}, ["lock-discipline"])
    assert len(res.new) == 1 and "_locked" in res.new[0].message


def test_lock_closure_defined_under_lock_is_still_unlocked(tmp_path):
    # a callback DEFINED inside `with self._lock` executes later, on
    # whatever thread resolves it, with no lock held — the pass must
    # not inherit the lexical lock into the nested def
    res = _run(tmp_path, {"pertgnn_tpu/serve/q.py": """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                threading.Thread(target=self.work).start()

            def work(self):
                with self._lock:
                    def cb(fut):
                        self.n += 1
                    register(cb)
    """}, ["lock-discipline"])
    assert len(res.new) == 1 and "self.n" in res.new[0].message


def test_lock_annotated_and_tuple_assignments_flagged(tmp_path):
    # `self.x: int = v` and `self.a, self.b = ...` mutate exactly like
    # plain assignment — the pass must not be dodged by an annotation
    res = _run(tmp_path, {"pertgnn_tpu/serve/q.py": _LOCK_BAD.replace(
        "self.count += 1",
        "self.count: int = 5\n            self.a, self.b = 1, 2")},
        ["lock-discipline"])
    assert sorted(v.key.split("@")[0] for v in res.new) == [
        "Q.a", "Q.b", "Q.count"]


def test_lock_container_mutation_flagged(tmp_path):
    res = _run(tmp_path, {"pertgnn_tpu/serve/q.py":
                          _LOCK_BAD.replace("self.count += 1",
                                            "self.pending.append(1)")},
               ["lock-discipline"])
    assert len(res.new) == 1 and ".append() call" in res.new[0].message


def test_lock_real_repo_allowlist_is_live():
    """Every allowlist entry must still name a real (class, attr) in
    the scoped files — a stale entry is a data race with a permission
    slip (the pass docstring's contract)."""
    import ast
    ctx = _repo_ctx()
    seen = set()
    for rel in ctx.files_under(*lock_discipline.SCOPE):
        tree = ctx.tree(rel)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                src = ctx.source(rel)
                for cls, attr in lock_discipline.ALLOWLIST:
                    if node.name == cls and f"self.{attr}" in src:
                        seen.add((cls, attr))
    assert seen == set(lock_discipline.ALLOWLIST)


# --- flag-config-drift ---------------------------------------------------


_CFG = """
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class ServeConfig:
        knob: int = 1
        {extra_field}

    @dataclasses.dataclass(frozen=True)
    class Config:
        serve: ServeConfig = ServeConfig()
"""

_COMMON = """
    def add_flags(p):
        p.add_argument("--knob", type=int, default=1)
        {extra_flag}

    def config_from_args(args):
        return (args.knob, {extra_read})
"""


def _flag_repo(tmp_path, extra_field="", extra_flag="", extra_read="0"):
    return {
        "pertgnn_tpu/config.py": _CFG.format(extra_field=extra_field),
        "pertgnn_tpu/cli/common.py": _COMMON.format(
            extra_flag=extra_flag or "pass", extra_read=extra_read),
    }


def test_flag_config_in_sync_is_clean(tmp_path):
    files = _flag_repo(tmp_path, extra_field="pad: int = 0",
                       extra_flag='p.add_argument("--pad", type=int)',
                       extra_read="args.pad")
    res = _run(tmp_path, files, ["flag-config-drift"])
    assert res.new == []


def test_flag_config_missing_flag_flagged(tmp_path):
    files = _flag_repo(tmp_path, extra_field="orphan: int = 0")
    res = _run(tmp_path, files, ["flag-config-drift"])
    assert [v.key for v in res.new] == ["field:serve.orphan"]


def test_flag_config_missing_field_flagged(tmp_path):
    files = _flag_repo(tmp_path,
                       extra_flag='p.add_argument("--ghost", type=int)',
                       extra_read="args.ghost")
    res = _run(tmp_path, files, ["flag-config-drift"])
    assert [v.key for v in res.new] == ["flag:ghost"]


def test_flag_config_unconsumed_flag_flagged(tmp_path):
    # parsed-but-never-read: the half of a wiring mistake that a pure
    # name match cannot see (the min_bucket_nodes lesson)
    files = _flag_repo(tmp_path, extra_field="pad: int = 0",
                       extra_flag='p.add_argument("--pad", type=int)')
    res = _run(tmp_path, files, ["flag-config-drift"])
    assert [v.key for v in res.new] == ["unconsumed:pad"]


def test_flag_config_real_repo_allowlists_are_live():
    """NOT_CLI / NOT_CONFIG / ALIASES entries must still reference real
    fields and flags — dead exemptions hide future drift."""
    ctx = _repo_ctx()
    fields = flag_config._config_fields(ctx)
    flags = flag_config._flags(ctx, flag_config.COMMON)
    for dotted in flag_config.NOT_CLI:
        assert dotted in fields, f"NOT_CLI names a gone field {dotted}"
    for flag in flag_config.NOT_CONFIG:
        assert flag in flags, f"NOT_CONFIG names a gone flag --{flag}"
    for flag, dotted in flag_config.ALIASES.items():
        assert flag in flags, f"ALIASES names a gone flag --{flag}"
        assert dotted in fields, f"ALIASES names a gone field {dotted}"


def test_flag_config_min_bucket_flags_exist():
    """The PR-8 fix this pass forced: the serve ladder's min rung knobs
    are CLI-reachable on the serve surface."""
    ctx = _repo_ctx()
    flags = flag_config._flags(ctx, flag_config.COMMON)
    assert "min_bucket_nodes" in flags and "min_bucket_edges" in flags


# --- bench.py --gate refusal ---------------------------------------------


def test_bench_gate_refuses_lint_failing_tree(tmp_path, monkeypatch,
                                              capsys):
    import bench
    import tools.graftlint as gl

    fake = driver.LintResult(
        new=[driver.Violation(rule="excepts", path="x.py", line=1,
                              message="boom")],
        baselined=[], elapsed_s=0.0, passes=["excepts"])
    monkeypatch.setattr(gl, "run_repo", lambda repo: fake)
    # a syntactically-valid result: usage validation runs FIRST (a
    # mistyped invocation must exit 2 without paying the lint), so the
    # refusal path needs a readable result to reach
    result = tmp_path / "result.json"
    result.write_text(json.dumps({"backend": "cpu", "value": 1.0,
                                  "attention_impl": "segment"}))
    rc = bench.gate_main([str(result)])
    out = capsys.readouterr().out
    assert rc == 1 and "graftlint" in out and "boom" in out


def test_bench_gate_skip_env_is_loud(monkeypatch, capsys):
    import bench

    monkeypatch.setenv("BENCH_GATE_SKIP_LINT", "1")
    assert bench._graftlint_refusal() == []
    assert "WITHOUT the graftlint check" in capsys.readouterr().err


# --- --changed-only (git-diff-scoped pre-commit runs) ---------------------


def test_pass_scopes_declared():
    """Every pass declares whether it is sound on a file subset — an
    undeclared pass would silently default to file scope and a future
    repo-contract pass could fabricate drift under --changed-only."""
    from tools.graftlint.passes import registry

    for name, mod in registry().items():
        assert getattr(mod, "PASS_SCOPE", None) in ("file", "repo"), name


def _git(repo, *args):
    import subprocess

    subprocess.run(["git", *args], cwd=repo, check=True,
                   capture_output=True,
                   env={**os.environ,
                        "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                        "GIT_COMMITTER_NAME": "t",
                        "GIT_COMMITTER_EMAIL": "t@t"})


def test_changed_only_scopes_to_the_diff(tmp_path, capsys):
    """Committed debt stays invisible; the CHANGED file's violation is
    caught — exactly the pre-commit contract."""
    bare = "try:\n    pass\nexcept:\n    pass\n"
    repo = _mini_repo(tmp_path, {"pertgnn_tpu/old.py": bare})
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    (tmp_path / "pertgnn_tpu" / "new.py").write_text(bare)
    # full run sees both files
    rc = cli_main(["--root", repo, "--no-baseline", "--json"])
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and len(doc["violations"]) == 2
    # --changed-only sees only the untracked file
    rc = cli_main(["--root", repo, "--no-baseline", "--json",
                   "--changed-only"])
    out = capsys.readouterr()
    doc = json.loads(out.out.strip().splitlines()[-1])
    assert rc == 1
    assert [v["path"] for v in doc["violations"]] == ["pertgnn_tpu/new.py"]
    assert "skips repo-contract" in out.err


def test_changed_only_refuses_explicit_repo_pass(capsys):
    rc = cli_main(["telemetry", "--changed-only", "--no-baseline"])
    assert rc == 2
    assert "cannot run under --changed-only" in capsys.readouterr().err


def test_changed_only_on_live_tree_is_clean_and_fast(capsys):
    t0 = time.perf_counter()
    rc = cli_main(["--changed-only", "--no-baseline"])
    assert rc == 0
    assert time.perf_counter() - t0 < 30
    capsys.readouterr()


# --- durable-write pass ---------------------------------------------------


_STORE_RAW = """
    import json
    import os

    import numpy as np

    def save(root, body, arr):
        path = os.path.join(root, "m.json")
        with open(path + ".new", "w") as f:
            json.dump(body, f)
        os.replace(path + ".new", path)
        np.save(os.path.join(root, "a.npy"), arr)

    def load(root):
        with open(os.path.join(root, "m.json")) as f:
            return json.load(f)
"""


def test_durable_write_raw_store_writes_flagged(tmp_path):
    """Every raw write primitive in a store module is a finding; the
    read in load() is untouched."""
    bad = _run(tmp_path, {"pertgnn_tpu/stream/store.py": _STORE_RAW},
               ["durable-write"])
    keys = sorted(v.key for v in bad.new)
    assert keys == ["np.save", "open:w", "os.replace"]
    assert all(v.rule == "durable-write" for v in bad.new)


def test_durable_write_outside_store_scope_is_clean(tmp_path):
    ok = _run(tmp_path, {"pertgnn_tpu/serve/engine.py": _STORE_RAW},
              ["durable-write"])
    assert ok.new == []


def test_durable_write_pragma_and_mode_kwarg(tmp_path):
    src = """
        def dump(path, data):
            with open(path, mode="ab") as f:  # graftlint: allow-durable-write
                f.write(data)

        def sneaky(path, m, data):
            with open(path, mode=m) as f:
                f.write(data)
    """
    r = _run(tmp_path, {"pertgnn_tpu/store/thing.py": src},
             ["durable-write"])
    # the pragma'd append is the reviewed exception; the dynamic mode
    # cannot be proven a read, so it counts as writing
    assert [v.key for v in r.new] == ["open:<dynamic>"]


def test_durable_write_live_tree_exceptions_all_pragmad():
    """The repo's own store modules are clean — every raw primitive
    that legitimately remains (durable.py's internals, scrub's
    quarantine rename, the watchdog crash dump) carries the pragma."""
    from tools.graftlint.passes import durable_write
    ctx = _repo_ctx()
    raw = durable_write.run(ctx)
    result = driver.run_passes(REPO, ["durable-write"], baseline_path="")
    assert result.new == [], "\n".join(str(v) for v in result.new)
    # the pass is not vacuous: it DID see pragma'd raw calls
    assert len(raw) >= 3
