"""graftshield (ISSUE 13): load generation, SLO shedding, brownout,
hedged dispatch, and elastic warm spares.

Four layers, cheapest first (the fleet-testing discipline of
tests/test_fleet.py):

1. the PURE decision functions — SLO class priority, lowest-class-first
   victim choice, brownout hysteresis, hedge threshold/worker choice,
   exclusion-aware dispatch — no queues, no threads, no clocks;
2. the open-loop LOAD GENERATOR — schedule determinism per seed, burst
   and diurnal envelopes, Zipf skew, SLO mix, and a replay against an
   injected front door (no fleet);
3. the ROUTER over INJECTED transports — the hedge race driven
   deterministically in BOTH orders (bit-safety: the future resolves
   exactly once to identical bits regardless of which leg lands
   first), retry exclusion of an observed-failing worker, class-aware
   eviction, brownout downgrade on the wire, and live add/remove
   membership — no sockets, no engines;
4. the AUTOSCALE controller over a fake router and injected clock —
   hysteresis hold/cooldown sequencing with zero sleeps.

Engine-dependent coverage (queue-level eviction, rung downgrade)
rides tests/test_fleet.py, which already owns the warm engine fixture;
the full chaos-storm integration is benchmarks/tail_bench.py.
"""

import math
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from pertgnn_tpu.config import FleetConfig
from pertgnn_tpu.fleet import loadgen, policy, shield
from pertgnn_tpu.fleet.autoscale import AutoscaleController
from pertgnn_tpu.fleet.policy import WorkerView
from pertgnn_tpu.fleet.router import FleetRouter
from pertgnn_tpu.fleet.transport import WorkerTransportError
from pertgnn_tpu.serve.errors import QueueFull, Shed
from pertgnn_tpu.telemetry.bus import NoopBus


# -- 1. pure decision functions ------------------------------------------

class TestSloClasses:
    def test_priority_order(self):
        assert shield.class_priority("critical") == 0
        assert shield.class_priority(shield.DEFAULT_CLASS) == 1
        assert shield.class_priority(shield.BEST_EFFORT) == 2

    def test_unknown_class_raises(self):
        with pytest.raises(ValueError, match="unknown SLO class"):
            shield.class_priority("platinum")

    def test_shed_is_a_queue_full(self):
        # pre-SLO callers match on QueueFull; Shed must stay catchable
        exc = Shed("full", slo="best_effort")
        assert isinstance(exc, QueueFull)
        assert exc.slo == "best_effort"


class TestShedVictim:
    def test_evicts_newest_of_lowest_class(self):
        pending = ["standard", "best_effort", "critical", "best_effort"]
        assert shield.shed_victim_index(pending, "critical") == 3

    def test_equal_class_never_evicts_peers(self):
        assert shield.shed_victim_index(["standard", "standard"],
                                        "standard") is None
        assert shield.shed_victim_index(["critical"], "critical") is None

    def test_lower_class_arrival_never_evicts(self):
        assert shield.shed_victim_index(["critical", "standard"],
                                        "best_effort") is None
        assert shield.shed_victim_index(["critical"],
                                        "standard") is None

    def test_standard_arrival_evicts_best_effort(self):
        assert shield.shed_victim_index(
            ["best_effort", "standard", "best_effort"], "standard") == 2

    def test_empty_pending(self):
        assert shield.shed_victim_index([], "critical") is None


class TestBrownout:
    def test_disabled_when_enter_ratio_zero(self):
        active, ev = shield.brownout_transition(
            False, 1.0, 10.0, 0.0, enter_ratio=0.0, exit_ratio=0.0)
        assert not active and ev is None

    def test_enter_exit_hysteresis(self):
        a, ev = shield.brownout_transition(
            False, 0.6, 0.0, 0.0, enter_ratio=0.5, exit_ratio=0.25)
        assert a and ev == "enter"
        # between exit and enter: stays active
        a, ev = shield.brownout_transition(
            True, 0.4, 1.0, 0.0, enter_ratio=0.5, exit_ratio=0.25)
        assert a and ev is None
        # below exit + past dwell: exits
        a, ev = shield.brownout_transition(
            True, 0.1, 2.0, 0.0, enter_ratio=0.5, exit_ratio=0.25)
        assert not a and ev == "exit"

    def test_min_dwell_blocks_flapping(self):
        a, ev = shield.brownout_transition(
            True, 0.0, 0.1, 0.0, enter_ratio=0.5, exit_ratio=0.25,
            min_dwell_s=0.5)
        assert a and ev is None  # too soon to exit

    def test_resolve_exit_ratio(self):
        assert shield.resolve_exit_ratio(0.5, 0.3) == 0.3
        assert shield.resolve_exit_ratio(0.5, 0.0) == 0.25


class TestHedgePolicy:
    def test_fixed_threshold_wins(self):
        assert policy.hedge_threshold_s(120.0, 0.9, []) == 0.12

    def test_adaptive_needs_samples(self):
        assert policy.hedge_threshold_s(0.0, 0.9, [0.01] * 5) == math.inf

    def test_adaptive_quantile(self):
        samples = [i / 100.0 for i in range(100)]  # 0..0.99
        thr = policy.hedge_threshold_s(0.0, 0.95, samples)
        assert 0.90 <= thr <= 0.97

    def test_off_when_unconfigured(self):
        assert policy.hedge_threshold_s(0.0, 0.0, [0.01] * 100) == \
            math.inf

    def test_choose_hedge_worker_excludes_primary(self):
        ws = [WorkerView("a", inflight_batches=0),
              WorkerView("b", inflight_batches=3, slots=2)]
        # a is the primary -> excluded; b is OVER its slot cap even
        # with the +1 hedge allowance -> nobody
        assert policy.choose_hedge_worker(ws, exclude={"a"}) is None
        ws[1] = WorkerView("b", inflight_batches=2, slots=2)
        # slots + 1 allowance admits b for a hedge
        assert policy.choose_hedge_worker(
            ws, exclude={"a"}).worker_id == "b"


class TestChooseWorkerExclusion:
    def test_exclusion_beats_earlier_completion(self):
        ws = [WorkerView("fast", ewma_batch_s=0.001),
              WorkerView("slow", ewma_batch_s=1.0)]
        assert policy.choose_worker(ws).worker_id == "fast"
        assert policy.choose_worker(
            ws, exclude={"fast"}).worker_id == "slow"

    def test_all_excluded_is_none(self):
        ws = [WorkerView("a"), WorkerView("b")]
        assert policy.choose_worker(ws, exclude={"a", "b"}) is None


# -- 2. the open-loop load generator -------------------------------------

POP_E = np.arange(50, dtype=np.int64)
POP_T = np.arange(50, dtype=np.int64) * 30_000


class TestSchedule:
    def test_deterministic_per_seed(self):
        spec = loadgen.LoadSpec(duration_s=3.0, base_rps=200, seed=3)
        s1 = loadgen.generate_schedule(spec, POP_E, POP_T)
        s2 = loadgen.generate_schedule(spec, POP_E, POP_T)
        for a, b in ((s1.t, s2.t), (s1.entry_ids, s2.entry_ids),
                     (s1.ts_buckets, s2.ts_buckets), (s1.slo, s2.slo)):
            np.testing.assert_array_equal(a, b)
        s3 = loadgen.generate_schedule(
            loadgen.LoadSpec(duration_s=3.0, base_rps=200, seed=4),
            POP_E, POP_T)
        assert len(s3) != len(s1) or not np.array_equal(s1.t, s3.t)

    def test_burst_windows_are_denser(self):
        spec = loadgen.LoadSpec(duration_s=4.0, base_rps=100,
                                burst_factor=8.0, burst_every_s=2.0,
                                burst_len_s=0.5, seed=0)
        s = loadgen.generate_schedule(spec, POP_E, POP_T)
        in_burst = ((s.t % 2.0) < 0.5).sum()
        out_burst = len(s) - in_burst
        # burst windows are 1/4 of the time at 8x the rate: they must
        # carry well over half the arrivals
        assert in_burst > out_burst

    def test_diurnal_envelope(self):
        spec = loadgen.LoadSpec(base_rps=100, diurnal_amp=0.5,
                                diurnal_period_s=10.0)
        # peak at t = period/4, trough at 3*period/4
        assert loadgen.rate_at(spec, 2.5) == pytest.approx(150.0)
        assert loadgen.rate_at(spec, 7.5) == pytest.approx(50.0)

    def test_zipf_skew(self):
        spec = loadgen.LoadSpec(duration_s=5.0, base_rps=400,
                                zipf_s=1.2, seed=1)
        s = loadgen.generate_schedule(spec, POP_E, POP_T)
        counts = np.bincount(s.entry_ids, minlength=len(POP_E))
        top = counts.max() / len(s)
        # rank-1 under Zipf(1.2) over 50 entries holds >> uniform share
        assert top > 3.0 / len(POP_E)

    def test_slo_mix_and_validation(self):
        spec = loadgen.LoadSpec(duration_s=2.0, base_rps=300, seed=0)
        s = loadgen.generate_schedule(spec, POP_E, POP_T)
        present = {s.slo_name(i) for i in range(len(s))}
        assert present == set(shield.SLO_CLASSES)
        bad = loadgen.LoadSpec(slo_mix=(("platinum", 1.0),))
        with pytest.raises(ValueError, match="unknown SLO class"):
            loadgen.generate_schedule(bad, POP_E, POP_T)


class TestReplay:
    def test_outcomes_recorded_and_open_loop(self):
        spec = loadgen.LoadSpec(duration_s=0.3, base_rps=300, seed=2)
        sched = loadgen.generate_schedule(spec, POP_E, POP_T)
        assert len(sched) > 10
        n_shed = 0

        def submit(eid, tsb, slo=None):
            nonlocal n_shed
            if eid % 7 == 0:  # a deterministic admission reject slice
                n_shed += 1
                raise Shed("full", slo=slo)
            fut: Future = Future()
            fut.set_result(float(eid) * 2.0)
            return fut

        res = loadgen.replay(submit, sched, bus=NoopBus(),
                             wait_timeout_s=10.0)
        assert res.offered == len(sched)
        assert res.submitted == len(sched) - n_shed
        assert res.unresolved == 0
        assert res.lost_futures() == 0
        assert res.error_counts().get("Shed", 0) == n_shed
        ok = np.isfinite(res.preds)
        np.testing.assert_array_equal(
            res.preds[ok], sched.entry_ids[ok].astype(np.float32) * 2)
        by_class = res.latency_summary_by_class(sched)
        assert sum(v["count"] for v in by_class.values()) == int(ok.sum())

    def test_late_resolution_counts_unresolved(self):
        sched = loadgen.generate_schedule(
            loadgen.LoadSpec(duration_s=0.05, base_rps=100, seed=5),
            POP_E, POP_T)
        holds = []

        def submit(eid, tsb, slo=None):
            fut: Future = Future()
            holds.append(fut)
            return fut

        res = loadgen.replay(submit, sched, bus=NoopBus(),
                             wait_timeout_s=0.2)
        assert res.unresolved == len(holds) > 0
        for f in holds:  # resolve so no thread leaks a pending future
            f.set_result(0.0)


# -- 3. the router over injected transports ------------------------------

def _probe_200(base_url, timeout_s):
    return 200, {}


def _mk_router(urls, post, cfg, probe=_probe_200):
    return FleetRouter(urls, lambda eid: (10, 10), (8, 10_000, 10_000),
                       cfg=cfg, transport_post=post,
                       transport_probe=probe)


def _rows(entries, value=2.0):
    return [{"pred": float(e) * value} for e in entries]


class TestHedgeRace:
    """The bit-safety property (ISSUE-13 satellite): duplicate
    dispatches of the same request return bit-identical predictions
    and the Future resolves EXACTLY once, raced deterministically in
    both orders with injected transports."""

    CFG = FleetConfig(hedge_quantile_ms=30.0,
                      router_flush_deadline_ms=0.0,
                      health_poll_interval_s=60.0,
                      dispatch_timeout_s=10.0)

    def _race(self, hedge_wins: bool):
        release_primary = threading.Event()
        hedge_returned = threading.Event()
        calls: list[str] = []
        calls_lock = threading.Lock()

        def post(base_url, entries, ts, timeout_s, trace=None,
                 slo=None, dg=None):
            with calls_lock:
                calls.append(base_url)
                nth = len(calls)
            if nth == 1 and hedge_wins:
                # primary leg: stall until the hedge has answered,
                # then return the SAME bits late
                assert release_primary.wait(10.0)
            elif nth == 1:
                # primary leg: straggle past the hedge threshold but
                # answer FIRST
                time.sleep(0.06)
            elif nth == 2 and not hedge_wins:
                # hedge leg: only answers after the primary settled
                assert hedge_returned.wait(10.0)
            return _rows(entries)

        with _mk_router({"wa": "http://a", "wb": "http://b"}, post,
                        self.CFG) as router:
            fut = router.submit(5, 0)
            if hedge_wins:
                assert fut.result(timeout=10.0) == 10.0
                release_primary.set()
            else:
                assert fut.result(timeout=10.0) == 10.0
                hedge_returned.set()
            # let the losing leg land before reading stats
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if len(calls) >= 2 and router.stats_dict()[
                        "dispatched_batches"] >= 1:
                    with router._lock:
                        legs = router._inflight_legs
                    if legs == 0:
                        break
                time.sleep(0.01)
            stats = router.stats_dict()
        assert len(calls) == 2, "the hedge leg never dispatched"
        assert stats["hedge_fired"] == 1
        assert stats["hedge_won"] == (1 if hedge_wins else 0)
        assert stats["served"] == 1 and stats["failed"] == 0
        assert fut.result() == 10.0  # still exactly the same bits

    def test_hedge_leg_wins(self):
        self._race(hedge_wins=True)

    def test_primary_wins_late_hedge_ignored(self):
        self._race(hedge_wins=False)


class TestRetryExclusion:
    """A flapping worker (transport fails, probe immediately
    re-admits) must not eat the same request twice: the retry excludes
    the observed-failing worker (ISSUE-13 satellite)."""

    def test_retry_never_returns_to_the_failing_worker(self):
        cfg = FleetConfig(router_flush_deadline_ms=0.0,
                          health_poll_interval_s=0.02,
                          probe_lost_after=1,
                          dispatch_timeout_s=5.0, max_requeues=3)
        w1_calls = []

        def post(base_url, entries, ts, timeout_s, trace=None,
                 slo=None, dg=None):
            if base_url == "http://w1":
                w1_calls.append(list(entries))
                raise WorkerTransportError("w1 flaps on dispatch")
            return _rows(entries)

        n = 5
        with _mk_router({"w1": "http://w1", "w2": "http://w2"}, post,
                        cfg) as router:
            for i in range(n):
                # between requests the probe re-admits w1 (it answers
                # 200) — without exclusion the retry could land on w1
                # again and burn requeue budget nondeterministically
                fut = router.submit(i + 1, 0)
                assert fut.result(timeout=10.0) == (i + 1) * 2.0
                time.sleep(0.06)  # let the prober re-admit w1
            stats = router.stats_dict()
        # every request failed on w1 exactly once and was served by w2
        # on its FIRST retry — one requeue per request, never two
        assert stats["served"] == n and stats["failed"] == 0
        assert stats["requeues"] == len(w1_calls)
        assert all(len(c) >= 1 for c in w1_calls)


class TestRouterSloAdmission:
    def test_evicts_lowest_class_and_rejects_with_shed(self):
        cfg = FleetConfig(max_pending=2,
                          router_flush_deadline_ms=60_000.0,
                          health_poll_interval_s=60.0,
                          dispatch_timeout_s=10.0)

        def post(base_url, entries, ts, timeout_s, trace=None,
                 slo=None, dg=None):
            return _rows(entries)

        with _mk_router({"w": "http://w"}, post, cfg) as router:
            f_std = router.submit(1, 0)
            f_be = router.submit(2, 0, slo="best_effort")
            # a critical arrival at a full pending set evicts the
            # NEWEST lowest-class request — f_be — never itself
            f_crit = router.submit(3, 0, slo="critical")
            assert isinstance(f_be.exception(timeout=5.0), Shed)
            assert f_be.exception().slo == "best_effort"
            with pytest.raises(Shed) as exc:
                # a best_effort arrival outranks nothing queued
                # ([standard, critical]) — it is the one shed
                router.submit(4, 0, slo="best_effort")
            assert exc.value.slo == "best_effort"
            # a second critical evicts the standard request (strictly
            # lower class) — lowest-class-first all the way up
            f_crit2 = router.submit(5, 0, slo="critical")
            assert isinstance(f_std.exception(timeout=5.0), Shed)
            with pytest.raises(Shed):
                # an all-critical pending set: peers never evict peers
                router.submit(6, 0, slo="critical")
            stats = router.stats_dict()
            assert stats["shed_by_class"]["best_effort"] == 2
            assert stats["shed_by_class"]["standard"] == 1
            assert stats["shed_by_class"]["critical"] == 1
            assert stats["pending"] == 2
        # close() drains the admitted requests to real predictions
        assert f_crit.result(timeout=5.0) == 6.0
        assert f_crit2.result(timeout=5.0) == 10.0


class TestRouterBrownout:
    def test_best_effort_downgraded_on_the_wire(self):
        cfg = FleetConfig(max_pending=4, brownout_enter_ratio=0.5,
                          router_flush_deadline_ms=60_000.0,
                          health_poll_interval_s=60.0,
                          dispatch_timeout_s=10.0)
        seen: list[tuple] = []

        def post(base_url, entries, ts, timeout_s, trace=None,
                 slo=None, dg=None):
            seen.append((list(entries), slo, dg))
            return _rows(entries)

        with _mk_router({"w": "http://w"}, post, cfg) as router:
            futs = [router.submit(1, 0, slo="best_effort"),
                    router.submit(2, 0),
                    router.submit(3, 0, slo="best_effort")]
            # occupancy 3/4 >= 0.5: the dispatch tick (the close drain
            # below) enters brownout and stamps downgrade verdicts
        for f in futs:
            assert np.isfinite(f.result(timeout=10.0))
        stats = router.stats_dict()
        assert stats["brownout_active"] is True
        entries_, slo_, dg_ = seen[0]
        assert dg_ == [True, False, True]  # best_effort only
        assert slo_ == ["best_effort", None, "best_effort"]


class TestElasticMembership:
    def test_add_and_remove_worker_live(self):
        cfg = FleetConfig(router_flush_deadline_ms=0.0,
                          health_poll_interval_s=60.0,
                          dispatch_timeout_s=10.0)

        def post(base_url, entries, ts, timeout_s, trace=None,
                 slo=None, dg=None):
            return _rows(entries)

        with _mk_router({"w1": "http://w1"}, post, cfg) as router:
            router.add_worker("spare0", "http://s0")
            assert "spare0" in router.stats_dict()["workers"]
            with pytest.raises(ValueError):
                router.add_worker("spare0", "http://dup")
            assert router.predict(7, 0, timeout=10.0) == 14.0
            router.remove_worker("spare0")
            router.remove_worker("spare0")  # idempotent
            stats = router.stats_dict()
            assert "spare0" not in stats["workers"]
            assert stats["worker_added"] == 1
            assert stats["worker_removed"] == 1
            # the shrunk fleet still serves
            assert router.predict(8, 0, timeout=10.0) == 16.0


def test_lock_discipline_scope_covers_the_new_fleet_modules():
    """The satellite pin: graftlint's lock-discipline pass must scan
    the new THREADED fleet modules (loadgen's replay callbacks, the
    autoscale controller, the hedger) — they all live under
    pertgnn_tpu/fleet/, so the prefix must stay in SCOPE, and the
    AutoscaleController allowlist entries must stay live (dead
    exemptions are a data race with a permission slip)."""
    import os

    from tools.graftlint.passes import lock_discipline

    assert "pertgnn_tpu/fleet/" in lock_discipline.SCOPE
    fleet_dir = os.path.dirname(loadgen.__file__)
    for mod in ("loadgen.py", "autoscale.py", "shield.py", "router.py"):
        assert os.path.exists(os.path.join(fleet_dir, mod))
    assert any(cls == "AutoscaleController"
               for cls, _attr in lock_discipline.ALLOWLIST)


# -- 4. the autoscale controller (fake router, injected clock) -----------

class _FakeRouter:
    def __init__(self):
        self.signal = 0.0
        self.added: list = []
        self.removed: list = []

    def queue_wait_signal_ms(self, window_s=2.0):
        return self.signal

    def add_worker(self, wid, url):
        self.added.append(wid)

    def remove_worker(self, wid):
        self.removed.append(wid)


def _mk_controller(router, max_spares=2, **kw):
    spawned = []

    def spawn(i):
        spawned.append(i)
        return f"spare{i}", f"http://spare{i}", object(), \
            {"compiles": 0, "arena_warm": True}

    stopped = []

    def stop(wid, handle):
        stopped.append(wid)

    ctrl = AutoscaleController(
        router, spawn_spare=spawn, stop_spare=stop,
        max_spares=max_spares, up_ms=50.0, down_ms=10.0, hold_s=1.0,
        cooldown_s=5.0, bus=NoopBus(), **kw)
    return ctrl, spawned, stopped


class TestAutoscale:
    def test_hold_then_spawn_then_cooldown_retire(self):
        router = _FakeRouter()
        ctrl, spawned, stopped = _mk_controller(router)
        router.signal = 100.0
        assert ctrl.step(0.0) is None     # over, hold starts
        assert ctrl.step(0.5) is None     # still holding
        assert ctrl.step(1.0) == "up"     # hold_s reached
        assert router.added == ["spare0"]
        assert ctrl.step(1.1) is None     # hold re-arms per spawn
        assert ctrl.step(2.2) == "up"     # second sustained crossing
        assert ctrl.step(3.5) is None     # at max_spares
        router.signal = 0.0
        assert ctrl.step(4.0) is None     # under, cooldown starts
        assert ctrl.step(8.9) is None
        assert ctrl.step(9.0) == "down"   # cooldown_s reached
        assert router.removed == ["spare1"]  # LIFO: newest first
        assert ctrl.step(9.1) is None     # cooldown re-arms
        assert ctrl.step(14.2) == "down"
        assert router.removed == ["spare1", "spare0"]
        assert stopped == ["spare1", "spare0"]
        st = ctrl.stats_dict()
        assert st["spawned"] == 2 and st["retired"] == 2
        assert st["spares"] == [] and not st["spawning"]

    def test_signal_dip_resets_the_hold(self):
        router = _FakeRouter()
        ctrl, spawned, _ = _mk_controller(router)
        router.signal = 100.0
        ctrl.step(0.0)
        router.signal = 0.0
        ctrl.step(0.5)                    # dip clears over_since
        router.signal = 100.0
        assert ctrl.step(0.9) is None     # hold restarts here
        assert ctrl.step(1.8) is None
        assert ctrl.step(1.95) == "up"
        assert spawned == [0]

    def test_spawn_failure_counted_and_retried(self):
        router = _FakeRouter()
        boom = [True]

        def spawn(i):
            if boom[0]:
                boom[0] = False
                raise RuntimeError("port bind race")
            return f"spare{i}", "http://s", object(), {"compiles": 0}

        ctrl = AutoscaleController(
            router, spawn_spare=spawn, stop_spare=lambda w, h: None,
            max_spares=1, up_ms=50.0, down_ms=10.0, hold_s=0.1,
            cooldown_s=5.0, bus=NoopBus())
        router.signal = 100.0
        ctrl.step(0.0)
        assert ctrl.step(0.2) is None     # spawn raised
        assert ctrl.stats_dict()["spawn_failed"] == 1
        ctrl.step(0.3)
        assert ctrl.step(0.5) == "up"     # retried on the next hold
        assert router.added == ["spare0"]

    def test_close_force_retires(self):
        router = _FakeRouter()
        ctrl, _, stopped = _mk_controller(router, max_spares=1)
        router.signal = 100.0
        ctrl.step(0.0)
        assert ctrl.step(1.0) == "up"
        ctrl.close()
        assert router.removed == ["spare0"]
        assert stopped == ["spare0"]
