"""Cold-start elimination (pertgnn_tpu/aot/): cache keying, the
serialized-executable store, and the precompile stage.

The load-bearing guarantees:
- a SECOND engine/process over the same config performs ZERO fresh
  compiles: every ladder rung deserializes from the store (asserted on
  the engine counters AND the aot.* telemetry events);
- deserialized executables answer bit-identically to freshly compiled
  ones;
- ANY drift in the key's ingredients (config, jax version, device kind,
  signature) changes the key — replaying a stale executable is
  impossible by construction, and the miss is diagnosed loudly;
- a corrupt/truncated store entry falls back to a fresh compile with a
  warning — never a crash.

Tests that pay more than one ladder/program compile are marked `slow`
(tier-1 runs `-m 'not slow'`; ROADMAP.md) so suite wall time does not
regress — the in-budget tests share ONE warmed module-scoped engine.
"""

from __future__ import annotations

import json
import os
import pickle

import numpy as np
import pytest

from pertgnn_tpu import aot, telemetry
from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import (CompileCacheConfig, Config, DataConfig,
                                IngestConfig, ModelConfig, ServeConfig,
                                TrainConfig)
from pertgnn_tpu.serve.engine import InferenceEngine
from pertgnn_tpu.train.loop import restore_target_state

SERVE = ServeConfig(bucket_growth=4.0, min_bucket_nodes=128,
                    min_bucket_edges=128, max_graphs_per_batch=4)


@pytest.fixture(scope="module", autouse=True)
def _reset_compile_cache():
    """These tests flip the GLOBAL persistent-compile-cache config onto
    module-temp dirs; restore the disabled default afterwards so the
    rest of the suite doesn't write cache entries into dead paths."""
    yield
    import jax

    jax.config.update("jax_compilation_cache_dir", None)


def _cfg(cache_dir: str, hidden: int = 8) -> Config:
    return Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=200, batch_size=16),
        model=ModelConfig(hidden_channels=hidden, num_layers=1),
        train=TrainConfig(label_scale=1000.0, scan_chunk=2),
        serve=SERVE,
        aot=CompileCacheConfig(cache_dir=cache_dir),
        graph_type="pert",
    )


class _RecordingBus(telemetry.NoopBus):
    """Collects (kind, name, tags) — enough to counter-assert aot.*."""

    def __init__(self):
        self.events: list[tuple[str, str, dict]] = []

    def counter(self, name, value=1, *, level=1, **tags):
        self.events.append(("counter", name, tags))

    def histogram(self, name, value, *, level=1, **tags):
        self.events.append(("histogram", name, tags))

    def count(self, name: str) -> int:
        return sum(1 for _, n, _t in self.events if n == name)


@pytest.fixture(scope="module")
def warmed(preprocessed, tmp_path_factory):
    """(cache_root, dataset, cfg, state, engine A) — engine A compiled
    the ladder once and persisted every rung; everything else in this
    module reuses it (ONE ladder compile for the in-budget tests)."""
    root = str(tmp_path_factory.mktemp("aot_store"))
    cfg = _cfg(root)
    ds = build_dataset(preprocessed, cfg)
    _model, state = restore_target_state(ds, cfg)
    bus = _RecordingBus()
    engine = InferenceEngine.from_dataset(ds, cfg, state,
                                          bus=bus).warmup()
    return root, ds, cfg, state, engine, bus


class TestKeys:
    def test_key_is_deterministic(self):
        env = {"jax": "1", "jaxlib": "1", "platform": "cpu",
               "device_kind": "cpu", "num_devices": 1}
        sig = {"leaves": ["(4,):float32"], "treedef": "*"}
        k1, _ = aot.cache_key(fn_id="f.v1", config={"a": 1},
                              args_sig=sig, env=env)
        k2, _ = aot.cache_key(fn_id="f.v1", config={"a": 1},
                              args_sig=sig, env=env)
        assert k1 == k2

    @pytest.mark.parametrize("mutate", [
        lambda kw: kw["config"].update(a=2),
        lambda kw: kw["env"].update(jax="2"),
        lambda kw: kw["env"].update(device_kind="TPU v5 lite"),
        lambda kw: kw["args_sig"].update(leaves=["(8,):float32"]),
        lambda kw: kw.update(fn_id="f.v2"),
    ])
    def test_any_ingredient_changes_key(self, mutate):
        kw = dict(fn_id="f.v1", config={"a": 1},
                  args_sig={"leaves": ["(4,):float32"], "treedef": "*"},
                  env={"jax": "1", "jaxlib": "1", "platform": "cpu",
                       "device_kind": "cpu", "num_devices": 1})
        base, _ = aot.cache_key(**kw)
        mutate(kw)
        changed, _ = aot.cache_key(fn_id=kw["fn_id"], config=kw["config"],
                                   args_sig=kw["args_sig"], env=kw["env"])
        assert changed != base

    def test_config_dataclasses_hash_by_value(self):
        sig = {"leaves": [], "treedef": "*"}
        env = {"jax": "1"}
        k1, _ = aot.cache_key(fn_id="f", config={"m": ModelConfig()},
                              args_sig=sig, env=env)
        k2, _ = aot.cache_key(fn_id="f", config={"m": ModelConfig()},
                              args_sig=sig, env=env)
        k3, _ = aot.cache_key(
            fn_id="f", config={"m": ModelConfig(hidden_channels=64)},
            args_sig=sig, env=env)
        assert k1 == k2 != k3

    def test_budget_change_changes_train_eval_key(self, preprocessed):
        """Compact programs bake max_nodes/max_edges into their scatter
        buffers but CompactBatch's (G,)-shaped signature can't see them:
        a budget-only change (same dataset, same batch_size) MUST miss,
        or yesterday's smaller program silently drops scatter rows."""
        import dataclasses

        from pertgnn_tpu.train.loop import _train_eval_key_config

        cfg = _cfg("")
        ds = build_dataset(preprocessed, cfg)
        cfg2 = cfg.replace(data=dataclasses.replace(
            cfg.data, max_nodes_per_batch=ds.budget.max_nodes + 128))
        ds2 = build_dataset(preprocessed, cfg2)
        assert ds2.budget != ds.budget
        env = {"jax": "1"}
        sig = {"leaves": ["(5,):int32"], "treedef": "*"}
        k1, c1 = aot.cache_key(
            fn_id="f", config=_train_eval_key_config(ds, cfg,
                                                     compact=True),
            args_sig=sig, env=env)
        k2, c2 = aot.cache_key(
            fn_id="f", config=_train_eval_key_config(ds2, cfg2,
                                                     compact=True),
            args_sig=sig, env=env)
        assert k1 != k2
        assert any(c.startswith("config.budget")
                   for c in aot.diff_components(c1, c2))

    def test_kernel_variant_knobs_change_train_eval_key(self,
                                                        preprocessed):
        """attention_impl / kernel block sizes / blocked_dense_max_cells
        (ISSUE 6) are ModelConfig fields baked into compiled programs as
        constants — the shape-identical abstract signature cannot see
        them, so each must land a different train/eval key (the same
        hardening the PR-3 review applied to budget/vocab). The legacy
        use_pallas_attention bool is key-relevant for the same reason."""
        import dataclasses

        from pertgnn_tpu.train.loop import _train_eval_key_config

        cfg = _cfg("")
        ds = build_dataset(preprocessed, cfg)
        env = {"jax": "1"}
        sig = {"leaves": ["(5,):int32"], "treedef": "*"}
        base_key, base_c = aot.cache_key(
            fn_id="f", config=_train_eval_key_config(ds, cfg,
                                                     compact=False),
            args_sig=sig, env=env)
        for field, value in (("attention_impl", "pallas_fused"),
                             ("attention_impl", "blocked_dense"),
                             ("kernel_block_n", 256),
                             ("kernel_block_e", 64),
                             ("blocked_dense_max_cells", 4096),
                             ("use_pallas_attention", True)):
            cfg2 = cfg.replace(model=dataclasses.replace(
                cfg.model, **{field: value}))
            k2, c2 = aot.cache_key(
                fn_id="f", config=_train_eval_key_config(ds, cfg2,
                                                         compact=False),
                args_sig=sig, env=env)
            assert k2 != base_key, field
            assert any(f"config.model.{field}" in c
                       for c in aot.diff_components(base_c, c2)), field

    def test_model_init_key_covers_vocab_sizes(self):
        """make_model bakes the dataset vocab sizes into embedding
        table shapes; same packed-sample signature + different vocab
        must be a different model_init key (stale tables would make
        clamped gathers silently wrong)."""
        from pertgnn_tpu.models.pert_model import make_model
        from pertgnn_tpu.train.loop import _model_init_key_config

        cfg = _cfg("")
        env = {"jax": "1"}
        sig = {"leaves": ["(2,):uint32"], "treedef": "*"}
        m1 = make_model(cfg.model, 30, 3, 5, 4)
        m2 = make_model(cfg.model, 30, 7, 5, 4)
        k1, c1 = aot.cache_key(
            fn_id="f", config=_model_init_key_config(cfg, m1),
            args_sig=sig, env=env)
        k2, c2 = aot.cache_key(
            fn_id="f", config=_model_init_key_config(cfg, m2),
            args_sig=sig, env=env)
        assert k1 != k2
        assert any("vocab.num_entries" in c
                   for c in aot.diff_components(c1, c2))

    def test_diff_components_names_the_change(self):
        _, c1 = aot.cache_key(fn_id="f", config={"hidden": 8},
                              args_sig={"leaves": [], "treedef": "*"},
                              env={"jax": "1"})
        _, c2 = aot.cache_key(fn_id="f", config={"hidden": 16},
                              args_sig={"leaves": [], "treedef": "*"},
                              env={"jax": "1"})
        changed = aot.diff_components(c1, c2)
        assert any("hidden" in c for c in changed)


class TestStoreRoundTrip:
    def test_first_engine_compiled_and_persisted(self, warmed):
        root, _ds, cfg, _state, engine, bus = warmed
        n = len(engine.ladder)
        assert engine.compiles == n
        assert engine.deserialized == 0
        # every rung missed (absent) then persisted an entry
        assert bus.count("aot.cache_miss") == n
        exe_root = os.path.join(root, "exe")
        slots = [d for d in os.listdir(exe_root)
                 if d.startswith("serve_rung")]
        assert len(slots) == n
        for d in slots:
            files = os.listdir(os.path.join(exe_root, d))
            assert any(f.endswith(".bin") for f in files)
            assert any(f.endswith(".json") for f in files)

    def test_second_engine_zero_fresh_compiles(self, warmed):
        """THE acceptance property: a fresh engine over the same config
        warms up purely by deserialization — counter-asserted on the
        engine, the aot.* bus events, and the XLA cache monitor."""
        _root, ds, cfg, state, engine_a, _bus = warmed
        bus = _RecordingBus()
        with telemetry.watch_xla_cache() as cache:
            engine_b = InferenceEngine.from_dataset(
                ds, cfg, state, bus=bus).warmup()
        n = len(engine_b.ladder)
        assert engine_b.compiles == 0
        assert engine_b.deserialized == n
        assert bus.count("aot.cache_hit") == n
        assert bus.count("aot.cache_miss") == 0
        # stablehlo replays must be disk-cache hits, not fresh compiles
        assert cache["misses"] == 0
        assert engine_b.stats_dict()["deserialized"] == n

    def test_deserialized_executable_matches_compiled(self, warmed):
        _root, ds, cfg, state, engine_a, _bus = warmed
        engine_b = InferenceEngine.from_dataset(ds, cfg, state).warmup()
        s = ds.splits["test"]
        a = engine_a.predict_many(s.entry_ids[:6], s.ts_buckets[:6])
        b = engine_b.predict_many(s.entry_ids[:6], s.ts_buckets[:6])
        np.testing.assert_array_equal(a, b)

    def test_queue_knobs_do_not_invalidate_rung_entries(self, warmed):
        """flush_deadline_ms / warmup are queue/transport knobs that the
        compiled step program never sees — tuning them must land on the
        SAME rung keys (no spurious invalidation, no recompiles)."""
        import dataclasses

        _root, ds, cfg, state, engine, _bus = warmed
        cfg2 = cfg.replace(serve=dataclasses.replace(
            cfg.serve, flush_deadline_ms=99.0, warmup=False))
        other = InferenceEngine.from_dataset(ds, cfg2, state)
        assert len(other.ladder) == len(engine.ladder)
        for i in range(len(engine.ladder)):
            name_a, key_a, _c, _a = engine._rung_entry(i)
            name_b, key_b, _c2, _a2 = other._rung_entry(i)
            assert (name_a, key_a) == (name_b, key_b)

    def test_serve_dtype_changes_rung_key(self, warmed):
        """serve_dtype is the ONE ServeConfig field baked into the rung
        step program (bf16 model dtype / int8 dequantize graph). bf16
        does not change the params signature at all — only the explicit
        key component can carry the invalidation, so a quantized
        executable can never replay for an f32 config."""
        import dataclasses

        _root, ds, cfg, state, engine, _bus = warmed
        name_a, key_a, comp_a, _args = engine._rung_entry(0)
        for dtype in ("bf16", "int8"):
            cfg2 = cfg.replace(serve=dataclasses.replace(
                cfg.serve, serve_dtype=dtype))
            other = InferenceEngine.from_dataset(ds, cfg2, state)
            name_b, key_b, comp_b, _args_b = other._rung_entry(0)
            assert name_a == name_b, dtype  # same shape slot
            assert key_a != key_b, dtype
            assert any("serve_dtype" in c
                       for c in aot.diff_components(comp_a, comp_b)), dtype

    def test_corrupt_entry_falls_back_to_fresh_compile(
            self, warmed, tmp_path, caplog):
        """Truncate one rung's payload: the next engine must log the
        corruption, recompile JUST that rung, and overwrite the entry —
        never crash."""
        import logging
        import shutil

        root, ds, cfg, state, _engine, _bus = warmed
        # work on a copy so the shared store stays intact
        copy = tmp_path / "store_copy"
        shutil.copytree(root, copy)
        exe_root = copy / "exe"
        slot = sorted(d for d in os.listdir(exe_root)
                      if d.startswith("serve_rung"))[0]
        [bin_path] = [exe_root / slot / f
                      for f in os.listdir(exe_root / slot)
                      if f.endswith(".bin")]
        bin_path.write_bytes(bin_path.read_bytes()[:64])  # truncate

        cfg2 = cfg.replace(aot=CompileCacheConfig(cache_dir=str(copy)))
        with caplog.at_level(logging.WARNING, logger="pertgnn_tpu"):
            engine = InferenceEngine.from_dataset(ds, cfg2,
                                                  state).warmup()
        n = len(engine.ladder)
        assert engine.compiles == 1  # only the corrupted rung
        assert engine.deserialized == n - 1
        assert any("corrupt" in r.message.lower()
                   for r in caplog.records)
        # the fresh save overwrote the truncated entry: next load works
        engine2 = InferenceEngine.from_dataset(ds, cfg2, state).warmup()
        assert engine2.compiles == 0
        assert engine2.deserialized == n

    def test_store_version_mismatch_is_corrupt_not_crash(
            self, warmed, tmp_path):
        root, ds, cfg, state, _engine, _bus = warmed
        store = aot.ExecutableStore(str(tmp_path / "vstore"))
        name, key = "prog", "k" * 32
        os.makedirs(os.path.join(store.root, name), exist_ok=True)
        with open(os.path.join(store.root, name, f"{key}.bin"),
                  "wb") as f:
            pickle.dump({"store_version": 999, "format": "pjrt"}, f)
        assert store.load(name, key, {}) is None


@pytest.mark.slow
class TestInvalidation:
    def test_model_change_misses_loudly_and_recompiles(
            self, preprocessed, tmp_path, caplog):
        """Same slot name (rung shapes unchanged), different model →
        different key → loud invalidation naming the changed field,
        fresh compile. Compiles two ladders: slow."""
        import logging

        root = str(tmp_path / "store")
        cfg8 = _cfg(root, hidden=8)
        ds8 = build_dataset(preprocessed, cfg8)
        _m, state8 = restore_target_state(ds8, cfg8)
        e8 = InferenceEngine.from_dataset(ds8, cfg8, state8).warmup()
        assert e8.compiles == len(e8.ladder)

        cfg16 = _cfg(root, hidden=16)
        ds16 = build_dataset(preprocessed, cfg16)
        _m, state16 = restore_target_state(ds16, cfg16)
        with caplog.at_level(logging.WARNING, logger="pertgnn_tpu"):
            e16 = InferenceEngine.from_dataset(ds16, cfg16,
                                               state16).warmup()
        assert e16.deserialized == 0
        assert e16.compiles == len(e16.ladder)
        inval = [r.message for r in caplog.records
                 if "invalidating" in r.message]
        assert inval and any("hidden_channels" in m for m in inval)


@pytest.mark.slow
class TestTrainPrograms:
    def test_precompile_then_fit_deserializes_programs(
            self, preprocessed, tmp_path):
        """precompile_train persists fit()'s init + train/eval programs;
        after clearing every IN-PROCESS jax cache (process-boundary
        stand-in), fit() resolves all three from the store — zero fresh
        model compiles, counter-asserted on the aot.* events. (The tiny
        eager EXECUTION-time ops a first epoch also runs are covered by
        the persistent XLA cache across real runs, not by precompile —
        benchmarks/coldstart_bench.py measures that end to end.)"""
        import jax

        from pertgnn_tpu.aot.precompile import precompile_train
        from pertgnn_tpu.train.loop import fit

        cfg = _cfg(str(tmp_path / "cache"))
        ds = build_dataset(preprocessed, cfg)
        stats = precompile_train(ds, cfg)
        assert stats["programs"]
        slots = set(os.listdir(tmp_path / "cache" / "exe"))
        assert {"model_init", "train_chunk_compact",
                "eval_chunk_compact"} <= slots

        jax.clear_caches()
        try:
            bus = _RecordingBus()
            _state, hist = fit(ds, cfg, epochs=1, bus=bus)
            assert bus.count("aot.cache_hit") == 3
            assert bus.count("aot.cache_miss") == 0
            assert hist and np.isfinite(hist[0]["train_qloss"])
            assert "ttfs_s" in hist[0]
        finally:
            jax.clear_caches()  # drop replay-form programs from memory

    def test_fit_results_match_with_and_without_store(
            self, preprocessed, tmp_path):
        """The store path may not change training numerics: one epoch
        with the AOT store vs the plain jit path, identical history."""
        from pertgnn_tpu.train.loop import fit

        cfg_plain = _cfg("")  # aot disabled
        ds = build_dataset(preprocessed, cfg_plain)
        _s1, h1 = fit(ds, cfg_plain, epochs=1)

        cfg_store = _cfg(str(tmp_path / "cache2"))
        ds2 = build_dataset(preprocessed, cfg_store)
        _s2, h2 = fit(ds2, cfg_store, epochs=1)
        for k in ("train_qloss", "train_mae", "valid_mae", "test_mae"):
            assert h1[0][k] == pytest.approx(h2[0][k], rel=1e-5), k


@pytest.mark.slow
def test_second_process_serve_warmup_zero_compiles(
        preprocessed, tmp_path):
    """The cross-PROCESS acceptance assert: a child process over the
    same store warms the ladder with zero fresh compiles. (The
    in-process variant is TestStoreRoundTrip's; this one cannot be
    faked by in-memory jit caches.)"""
    import subprocess
    import sys

    root = str(tmp_path / "store")
    cfg = _cfg(root)
    ds = build_dataset(preprocessed, cfg)
    _m, state = restore_target_state(ds, cfg)
    InferenceEngine.from_dataset(ds, cfg, state).warmup()

    code = f"""
import json
import numpy as np
from pertgnn_tpu import telemetry
from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import (CompileCacheConfig, Config, DataConfig,
                                IngestConfig, ModelConfig, ServeConfig,
                                TrainConfig)
from pertgnn_tpu.ingest import synthetic
from pertgnn_tpu.ingest.preprocess import preprocess
from pertgnn_tpu.serve.engine import InferenceEngine
from pertgnn_tpu.train.loop import restore_target_state

cfg = Config(
    ingest=IngestConfig(min_traces_per_entry=10),
    data=DataConfig(max_traces=200, batch_size=16),
    model=ModelConfig(hidden_channels=8, num_layers=1),
    train=TrainConfig(label_scale=1000.0, scan_chunk=2),
    serve=ServeConfig(bucket_growth=4.0, min_bucket_nodes=128,
                      min_bucket_edges=128, max_graphs_per_batch=4),
    aot=CompileCacheConfig(cache_dir={root!r}),
    graph_type="pert",
)
data = synthetic.generate(synthetic.SyntheticSpec(
    num_microservices=30, num_entries=3, patterns_per_entry=3,
    traces_per_entry=40, seed=7))
pre = preprocess(data.spans, data.resources, cfg.ingest)
ds = build_dataset(pre, cfg)
_m, state = restore_target_state(ds, cfg)
with telemetry.watch_xla_cache() as cache:
    engine = InferenceEngine.from_dataset(ds, cfg, state).warmup()
print(json.dumps({{"compiles": engine.compiles,
                   "deserialized": engine.deserialized,
                   "buckets": len(engine.ladder),
                   "xla_misses": cache["misses"]}}))
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["compiles"] == 0
    assert row["deserialized"] == row["buckets"]
    assert row["xla_misses"] == 0


class TestCompileCacheConfig:
    def test_disabled_by_default(self):
        assert not CompileCacheConfig().enabled
        assert CompileCacheConfig(cache_dir="/x").enabled

    def test_cli_flags_round_trip(self):
        import argparse

        from pertgnn_tpu.cli.common import add_aot_flags, config_from_args
        from pertgnn_tpu.cli.common import (add_ingest_flags,
                                            add_model_train_flags)

        p = argparse.ArgumentParser()
        add_ingest_flags(p)
        add_model_train_flags(p)
        add_aot_flags(p)
        args = p.parse_args(["--compile_cache_dir", "/tmp/c",
                             "--aot_min_compile_time_s", "0.5",
                             "--no_serialize_executables"])
        cfg = config_from_args(args)
        assert cfg.aot.cache_dir == "/tmp/c"
        assert cfg.aot.min_compile_time_s == 0.5
        assert cfg.aot.serialize_executables is False

    def test_store_from_config_respects_flags(self, tmp_path):
        assert aot.store_from_config(CompileCacheConfig()) is None
        cfg = CompileCacheConfig(cache_dir=str(tmp_path),
                                 serialize_executables=False)
        assert aot.store_from_config(cfg) is None
        cfg = CompileCacheConfig(cache_dir=str(tmp_path))
        store = aot.store_from_config(cfg)
        assert store is not None
        assert os.path.isdir(store.root)


class TestConfigMismatchSatellite:
    """ADVICE #3: output-relevant ingest fields join the sidecar
    cross-check; sequence fields compare list-vs-tuple safely."""

    def test_ingest_fields_checked(self):
        import dataclasses

        from pertgnn_tpu.train.checkpoint import config_mismatches

        cfg = Config()
        saved = dataclasses.asdict(cfg)
        saved["ingest"]["ts_bucket_ms"] = 60_000
        saved["ingest"]["min_resource_coverage"] = 0.9
        mism, _unknown = config_mismatches(saved, cfg)
        keys = {k for k, _a, _b in mism}
        assert "ingest.ts_bucket_ms" in keys
        assert "ingest.min_resource_coverage" in keys

    def test_resource_aggs_tuple_vs_json_list_not_a_mismatch(self):
        import dataclasses

        from pertgnn_tpu.train.checkpoint import config_mismatches

        cfg = Config()
        saved = json.loads(json.dumps(dataclasses.asdict(cfg)))
        # JSON round-trip turns the tuple into a list — must NOT flag
        assert isinstance(saved["ingest"]["resource_aggs"], list)
        mism, _ = config_mismatches(saved, cfg)
        assert not [k for k, _a, _b in mism
                    if k == "ingest.resource_aggs"]
        saved["ingest"]["resource_aggs"] = ["max", "min"]
        mism, _ = config_mismatches(saved, cfg)
        assert [k for k, _a, _b in mism if k == "ingest.resource_aggs"]

    def test_old_sidecar_without_ingest_warns_not_walls(self):
        from pertgnn_tpu.train.checkpoint import config_mismatches

        mism, unknown = config_mismatches({"graph_type": "span"},
                                          Config())
        assert not [k for k, _a, _b in mism if k.startswith("ingest.")]
        assert any(k.startswith("ingest.") for k in unknown)


class TestFlopsSatellite:
    def test_kind_lookup_resolves_known_tpus(self):
        from pertgnn_tpu.utils.flops import (peak_flops_for_kind,
                                             peak_hbm_bw_for_kind)

        assert peak_flops_for_kind("TPU v5 lite") == 197e12
        assert peak_flops_for_kind("TPU v4") == 275e12
        assert peak_hbm_bw_for_kind("TPU v5 lite") == 819e9
        # CPU / unknown stay honestly null
        assert peak_flops_for_kind("cpu") is None
        assert peak_flops_for_kind("") is None
        assert peak_flops_for_kind(None) is None

    def test_finalizer_resolves_peaks_from_recorded_kind(self):
        """A salvaged partial that recorded device_kind but predates
        the peak fields must still produce MFU/MBU."""
        import bench

        result = bench._assemble_result(
            fit_w=[100.0, 100.0, 100.0], ceil_w=[], cceil_w=[],
            unstaged_w=[], flops_per_graph=1e9, bytes_per_graph=1e6,
            baseline=10.0, backend="tpu", fallback=False,
            train_graphs=100, partial_capture=True,
            device_kind="TPU v5 lite")
        assert result["peak_flops_per_chip"] == 197e12
        assert result["mfu_pct"] is not None
        assert result["mbu_pct"] is not None
        assert result["device_kind"] == "TPU v5 lite"

    def test_cpu_run_stays_null(self):
        import bench

        result = bench._assemble_result(
            fit_w=[100.0], ceil_w=[], cceil_w=[], unstaged_w=[],
            flops_per_graph=1e9, bytes_per_graph=1e6, baseline=10.0,
            backend="cpu", fallback=True, train_graphs=100,
            device_kind="")
        assert result["mfu_pct"] is None
        assert result["mbu_pct"] is None
