"""Batching-layer tests: mixtures, featurization, fixed-shape packing."""

import numpy as np
import pandas as pd
import pytest

from pertgnn_tpu.batching.dataset import build_dataset, split_indices
from pertgnn_tpu.batching.featurize import ResourceLookup
from pertgnn_tpu.batching.mixture import build_mixtures
from pertgnn_tpu.graphs.construct import build_runtime_graphs
from pertgnn_tpu.ingest.assemble import assemble


def test_split_indices_positional():
    parts = split_indices(10, (0.6, 0.2, 0.2))
    assert [len(p) for p in parts] == [6, 2, 2]
    assert parts[0][0] == 0 and parts[2][-1] == 9
    # rounding remainder goes to the last split (reference trailing slice)
    parts = split_indices(11, (0.6, 0.2, 0.2))
    assert [len(p) for p in parts] == [6, 2, 3]


def test_resource_lookup_conventions():
    res = pd.DataFrame({
        "timestamp": [0, 0], "msname": [1, 2],
        **{f"f{i}": [float(i), float(i) * 10] for i in range(8)},
    })
    res.columns = ["timestamp", "msname"] + [f"f{i}" for i in range(8)]
    lk = ResourceLookup(res, missing_indicator_is_one=True)
    x = lk(np.array([0, 0, 30000]), np.array([1, 3, 1]))
    assert x.shape == (3, 9)
    assert x[0, -1] == 0.0 and x[0, 0] == 0.0 and x[0, 7] == 7.0
    assert x[1, -1] == 1.0 and (x[1, :-1] == 0).all()   # ms missing
    assert x[2, -1] == 1.0                              # bucket missing
    lk2 = ResourceLookup(res, missing_indicator_is_one=False)
    x2 = lk2(np.array([0, 0]), np.array([1, 3]))
    assert x2[0, -1] == 1.0 and x2[1, -1] == 0.0


class TestMixtures:
    @pytest.fixture(scope="class")
    def mixtures(self, preprocessed):
        table = assemble(preprocessed)
        graphs = build_runtime_graphs(preprocessed, table, "span")
        return build_mixtures(graphs, table.entry2runtimes), table, graphs

    def test_block_diag_layout(self, mixtures):
        mixes, table, graphs = mixtures
        for entry, (rt_ids, probs) in table.entry2runtimes.items():
            m = mixes[entry]
            assert m.num_nodes == sum(graphs[int(r)].num_nodes for r in rt_ids)
            assert m.num_edges == sum(graphs[int(r)].num_edges for r in rt_ids)
            # edges stay within their pattern's node block
            sizes = np.array([graphs[int(r)].num_nodes for r in rt_ids])
            bounds = np.concatenate([[0], np.cumsum(sizes)])
            blk_s = np.searchsorted(bounds, m.senders, side="right") - 1
            blk_r = np.searchsorted(bounds, m.receivers, side="right") - 1
            assert (blk_s == blk_r).all()

    def test_pert_feature_mask_last_stage_copy_only(self, preprocessed):
        """The reference's live get_x features only the LAST stage-copy of
        each microservice in a PERT graph (pert_gnn.py:56 dict-comp
        overwrite — found by executing the reference's own driver,
        benchmarks/parity/reference_driver_crosscheck.py). Default must
        match; `feature_all_stage_copies=True` restores full features."""
        table = assemble(preprocessed)
        graphs = build_runtime_graphs(preprocessed, table, "pert")
        mixes = build_mixtures(graphs, table.entry2runtimes)
        saw_within_graph_duplicate = False
        for entry, (rt_ids, _) in table.entry2runtimes.items():
            m = mixes[entry]
            assert m.feature_mask.dtype == bool
            off = 0
            # decompose into per-graph blocks: the rule is per GRAPH
            for rid in rt_ids:
                size = graphs[int(rid)].num_nodes
                block_ms = m.ms_id[off:off + size]
                block_mask = m.feature_mask[off:off + size]
                # the exact reference rule: True iff last occurrence of
                # this ms WITHIN the graph (pert_gnn.py:56)
                expected = np.zeros(size, dtype=bool)
                expected[[int(np.where(block_ms == v)[0][-1])
                          for v in np.unique(block_ms)]] = True
                np.testing.assert_array_equal(block_mask, expected)
                if len(np.unique(block_ms)) < size:
                    saw_within_graph_duplicate = True
                off += size
        assert saw_within_graph_duplicate, \
            "corpus must exercise within-graph stage duplication"
        # all-copies flag restores full featurization
        full = build_mixtures(graphs, table.entry2runtimes,
                              feature_all_stage_copies=True)
        assert all(mm.feature_mask.all() for mm in full.values())
        # span graphs have unique ms per node -> mask all-True by default
        sgraphs = build_runtime_graphs(preprocessed, table, "span")
        smixes = build_mixtures(sgraphs, table.entry2runtimes)
        assert all(mm.feature_mask.all() for mm in smixes.values())

    def test_per_node_prob_weighting_sums_to_one(self, mixtures):
        """sum over nodes of prob/size == sum over patterns of prob == 1 —
        the invariant behind the model's prob-weighted pooling
        (/root/reference/model.py:106-107)."""
        mixes, _, _ = mixtures
        for m in mixes.values():
            total = (m.pattern_prob / m.pattern_size).sum()
            assert total == pytest.approx(1.0, rel=1e-5)


class TestPacking:
    @pytest.fixture(scope="class")
    def ds(self, preprocessed, small_config):
        return build_dataset(preprocessed, small_config)

    def test_fixed_shapes(self, ds):
        shapes = set()
        for b in ds.batches("train"):
            shapes.add(tuple(np.shape(v) for v in b))
        assert len(shapes) == 1  # one static shape -> one compile

    def test_masks_consistent(self, ds):
        for b in ds.batches("train"):
            n_valid = int(b.node_mask.sum())
            e_valid = int(b.edge_mask.sum())
            g_valid = int(b.graph_mask.sum())
            assert g_valid > 0
            # pad nodes map to the reserved pad graph slot
            assert (b.node_graph[~b.node_mask] == b.num_graphs - 1).all()
            assert not b.graph_mask[-1]  # pad slot never a real graph
            # valid edges point at valid nodes
            assert b.node_mask[b.senders[b.edge_mask]].all()
            assert b.node_mask[b.receivers[b.edge_mask]].all()
            # per valid graph, mixture weights sum to 1
            w = np.zeros(b.num_graphs)
            np.add.at(w, b.node_graph[b.node_mask],
                      (b.pattern_prob / b.pattern_size)[b.node_mask])
            np.testing.assert_allclose(w[b.graph_mask], 1.0, rtol=1e-4)

    def test_features_match_lookup(self, ds):
        b = next(ds.batches("valid"))
        # recompute one graph's features directly
        g0_nodes = (b.node_graph == 0) & b.node_mask
        entry = int(b.entry_id[0])
        mix = ds.mixtures[entry]
        np.testing.assert_array_equal(b.ms_id[g0_nodes], mix.ms_id)

    def test_epoch_covers_all_examples(self, ds):
        total = sum(int(b.graph_mask.sum()) for b in ds.batches("train"))
        assert total == len(ds.splits["train"])

    def test_budget_headroom_sizing(self, ds, preprocessed, small_config):
        """derive_budget scales node/edge budgets with `headroom` (floored
        at the largest single mixture, 128-aligned); DataConfig.budget_
        headroom reaches it through build_dataset."""
        import dataclasses

        from pertgnn_tpu.batching.pack import derive_budget

        s = np.concatenate([ds.splits[n].entry_ids
                            for n in ("train", "valid", "test")])
        bs = ds.config.data.batch_size
        lo = derive_budget(ds.mixtures, s, bs, headroom=1.1)
        hi = derive_budget(ds.mixtures, s, bs, headroom=1.3)
        # monotone in headroom (128-rounding may collapse small budgets)
        assert hi.max_nodes >= lo.max_nodes and hi.max_edges >= lo.max_edges
        assert lo.max_nodes % 128 == 0 and lo.max_edges % 128 == 0
        sizes = np.array([ds.mixtures[int(e)].num_nodes for e in s])
        assert lo.max_nodes > sizes.max()  # largest mixture always fits
        cfg = small_config.replace(data=dataclasses.replace(
            small_config.data, budget_headroom=2.0))
        wide = build_dataset(preprocessed, cfg)
        assert wide.budget.max_nodes > ds.budget.max_nodes  # 2.0 ≫ 1.1

    def test_shuffle_changes_order_not_content(self, ds):
        a = [b.y[b.graph_mask] for b in ds.batches("train", shuffle=True,
                                                   seed=1)]
        c = [b.y[b.graph_mask] for b in ds.batches("train")]
        sa = np.sort(np.concatenate(a))
        sc = np.sort(np.concatenate(c))
        np.testing.assert_allclose(sa, sc)


def test_num_batches_matches_iteration(preprocessed, small_config):
    ds = build_dataset(preprocessed, small_config)
    for split in ("train", "valid", "test"):
        assert ds.num_batches(split) == sum(1 for _ in ds.batches(split))


class TestArenaPacker:
    """The vectorized arena path (`Dataset.batches`) must be bitwise
    identical to the readable per-example packer (`Dataset.batches_slow`)."""

    @pytest.fixture(scope="class")
    def ds(self, preprocessed, small_config):
        return build_dataset(preprocessed, small_config)

    @pytest.mark.parametrize("split,shuffle,seed", [
        ("train", False, 0), ("train", True, 3), ("valid", False, 0),
        ("test", False, 0)])
    def test_fast_slow_parity(self, ds, split, shuffle, seed):
        fast = list(ds.batches(split, shuffle=shuffle, seed=seed))
        slow = list(ds.batches_slow(split, shuffle=shuffle, seed=seed))
        assert len(fast) == len(slow)
        for f, s in zip(fast, slow):
            for name in f._fields:
                np.testing.assert_array_equal(
                    getattr(f, name), getattr(s, name), err_msg=name)

    def test_fast_slow_parity_with_node_depth(self, preprocessed,
                                              small_config):
        import dataclasses
        from pertgnn_tpu.config import ModelConfig
        cfg = dataclasses.replace(small_config,
                                  model=ModelConfig(use_node_depth=True))
        ds = build_dataset(preprocessed, cfg)
        fast = list(ds.batches("train", shuffle=True, seed=9))
        slow = list(ds.batches_slow("train", shuffle=True, seed=9))
        for f, s in zip(fast, slow):
            np.testing.assert_array_equal(f.x, s.x)

    def test_small_slab_crosses_batches(self, ds):
        """Slab boundaries must not change the stream."""
        from pertgnn_tpu.batching.arena import pack_epoch
        s = ds.splits["train"]
        whole = list(ds.batches("train"))
        slabbed = list(pack_epoch(
            ds.arena(), ds._feat_arena("train"), s.entry_ids, s.ts_buckets,
            s.ys, ds.budget, slab_batches=1))
        assert len(whole) == len(slabbed)
        for f, s_ in zip(whole, slabbed):
            for name in f._fields:
                np.testing.assert_array_equal(getattr(f, name),
                                              getattr(s_, name))

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_assign_batches_fast_path_matches_scalar(self, seed):
        """The cumsum fast path must reproduce the scalar greedy rule
        exactly whenever its no-overflow precondition holds."""
        from pertgnn_tpu.batching.arena import assign_batches
        from pertgnn_tpu.batching.pack import BatchBudget

        rng = np.random.default_rng(seed)
        nc = rng.integers(3, 12, size=500)
        ec = rng.integers(2, 20, size=500)

        def scalar_greedy(nc, ec, budget):
            b = g = n = e = 0
            out = []
            for cn, ce in zip(nc.tolist(), ec.tolist()):
                if (g + 1 > budget.max_graphs or n + cn > budget.max_nodes
                        or e + ce > budget.max_edges):
                    b += 1
                    g = n = e = 0
                out.append((b, g, n, e))
                g, n, e = g + 1, n + cn, e + ce
            return tuple(np.array(c) for c in zip(*out))

        # fast-path regime: budgets sized so node/edge never bind
        roomy = BatchBudget(max_graphs=16, max_nodes=16 * 12, max_edges=16 * 20)
        got = assign_batches(nc, ec, roomy)
        want = scalar_greedy(nc, ec, roomy)
        for a, b_, name in zip(got, want, ("batch", "slot", "noff", "eoff")):
            np.testing.assert_array_equal(a, b_, err_msg=name)

        # binding regime: budgets that DO bind mid-group -> scalar loop
        tight = BatchBudget(max_graphs=16, max_nodes=60, max_edges=90)
        got_t = assign_batches(nc, ec, tight)
        want_t = scalar_greedy(nc, ec, tight)
        for a, b_, name in zip(got_t, want_t, ("batch", "slot", "noff",
                                               "eoff")):
            np.testing.assert_array_equal(a, b_, err_msg=name)

    def test_eval_epoch_cached(self, ds):
        a = list(ds.batches("valid"))
        b = list(ds.batches("valid"))
        # identical objects — the deterministic split is packed once
        assert all(x.x is y.x for x, y in zip(a, b))

    def test_oversize_example_raises(self, ds):

        from pertgnn_tpu.batching.pack import BatchBudget
        tiny = BatchBudget(max_graphs=4, max_nodes=2, max_edges=2)
        s = ds.splits["train"]
        with pytest.raises(ValueError, match="exceeds"):
            list(pack_epoch_with(ds, s, tiny))


def pack_epoch_with(ds, s, budget):
    from pertgnn_tpu.batching.arena import pack_epoch
    return pack_epoch(ds.arena(), ds._feat_arena("train"), s.entry_ids,
                      s.ts_buckets, s.ys, budget)
