"""tools/graftaudit: the jaxpr/StableHLO-level program auditor, run
over the stack's REAL traced programs in tier-1 (docs/LINTS.md).

Fixture tests build miniature ProgramSpecs around tiny jitted
functions (the driver only needs a jaxpr + role metadata); THE gate is
test_repo_audits_clean_within_budget, which enumerates every serve
ladder rung x serve_dtype x attention_impl plus the train/eval/init
and sharded programs and audits them inside a wall-clock budget. Each
pass also has a negative pin — a fixture it MUST flag — so the proof
machinery can never rot into a vacuous pass.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tools.graftaudit import driver  # noqa: E402
from tools.graftaudit.cli import main as cli_main  # noqa: E402
from tools.graftaudit.passes import (collective_audit,  # noqa: E402
                                     donation, dtype_flow,
                                     host_interop, padding_taint,
                                     registry)
from tools.graftaudit.programs import (ProgramSpec, Role,  # noqa: E402
                                       build_programs)

BUDGET_S = 60.0  # the ISSUE-10 acceptance bound; measured ~12 s

N, F, G = 8, 4, 3


def _serve_spec(fn, name="serve/f32/mini/rung0", tags=("serve", "f32"),
                out_discard=("graph",), extra_roles=(),
                extra_avals=()):
    """A serve-shaped mini program: params w (F,), node data x (N, F),
    node_mask (N,), node_graph (N,) routing to graphs, plus whatever
    extra args the fixture needs. Output contract mirrors the engine:
    graph-pad lanes are discarded."""
    avals = (jax.ShapeDtypeStruct((F,), jnp.float32),
             jax.ShapeDtypeStruct((N, F), jnp.float32),
             jax.ShapeDtypeStruct((N,), jnp.bool_),
             jax.ShapeDtypeStruct((N,), jnp.int32)) + tuple(extra_avals)
    roles = [Role(kind="param", path="w"),
             Role(kind="data", cls="node", path="x"),
             Role(kind="mask", cls="node", path="node_mask"),
             Role(kind="route", cls="node", target="graph",
                  path="node_graph")] + list(extra_roles)
    traced = jax.jit(fn).trace(*avals)
    return ProgramSpec(name=name, tags=frozenset(tags),
                       jaxpr=traced.jaxpr, invar_roles=roles,
                       out_discard=frozenset(out_discard))


def _audit(specs, passes=None):
    return driver.run_passes(list(specs), passes, baseline=set())


# --- THE tier-1 gate -----------------------------------------------------


def test_repo_audits_clean_within_budget():
    """Every real program audits clean, inside the budget, with full
    coverage of the serve matrix and the train/eval/init/sharded
    programs — the ISSUE-10 acceptance criterion."""
    from pertgnn_tpu.config import ATTENTION_IMPLS, SERVE_DTYPES

    t0 = time.perf_counter()
    result = driver.run_repo()
    elapsed = time.perf_counter() - t0
    assert result.ok, json.dumps(result.as_dict(), indent=1)
    assert elapsed < BUDGET_S, f"audit took {elapsed:.1f}s"
    names = set(result.programs)
    for dtype in SERVE_DTYPES:
        for impl in ATTENTION_IMPLS:
            assert any(n.startswith(f"serve/{dtype}/{impl}/")
                       for n in names), (dtype, impl, names)
    rungs = {n.rsplit("/", 1)[1] for n in names
             if n.startswith("serve/f32/segment/")}
    assert len(rungs) >= 2, f"ladder enumeration collapsed: {rungs}"
    assert "init/model_init" in names
    assert any(n.startswith("train/") for n in names)
    assert any(n.startswith("eval/") for n in names)
    assert "sharded/train_step_dp" in names
    assert "sharded/train_step_edge_shard" in names
    # the ISSUE-11 satellite: the warm-restart fine-tune program
    # (stream/continual.py, traced through the continual module's own
    # construction over a REAL base+delta window dataset) is a
    # first-class audit subject — donation/dtype-flow/host-interop
    # coverage extends to continual training mechanically
    assert any(n.startswith("continual/finetune_") for n in names), names
    # the ISSUE-15 satellite: the lens serving programs are audited —
    # the multi-quantile (non-crossing head, (G, T) output) and the
    # local-pred-returning (attribution) variants; the latter KEEPS
    # node lanes, so a clean audit here IS the static proof that pad
    # rows are pinned to -inf before any top-k can see them
    assert any(n.startswith("lens/quantile/") for n in names), names
    assert any(n.startswith("lens/local/") for n in names), names
    # the ISSUE-18 satellite: the giant-corpus scale-out programs —
    # the sharded-merge collectives (collective-audit proves their only
    # axis name is a mesh axis), the accumulated SAR train step
    # (donation + no-stray-collective), and the scan-free per-bucket
    # body with full invar roles (padding-taint proves a zero-masked
    # padding bucket cannot leak into the accumulated sums)
    assert "scale/allreduce_sum" in names, names
    assert "scale/allreduce_min" in names, names
    assert "scale/sar_step_packed" in names, names
    assert "scale/sar_bucket_terms" in names, names


def test_no_baseline_file():
    """The tree audits clean with NO accepted debt — the baseline file
    exists for emergencies, not as a parking lot (docs/LINTS.md)."""
    assert not os.path.exists(driver.DEFAULT_BASELINE)


def test_allowlist_entries_are_live():
    """Every ALLOWLIST entry must still suppress a live finding — a
    dead exemption is debt nobody is tracking."""
    result = driver.run_repo()
    hits = result.allowlist_hits()
    dead = [driver.ALLOWLIST[i][:2]
            for i in range(len(driver.ALLOWLIST)) if i not in hits]
    assert not dead, f"dead allowlist entries: {dead}"
    # and the suppressed findings are exactly the documented limit:
    # the Pallas call boundary
    assert all("pallas" in v.path for v, _r in result.allowed)


def test_audit_emits_telemetry():
    """audit.programs / audit.violations / audit.seconds reach the bus
    (the rows docs/OBSERVABILITY.md documents and telemetry-drift
    keeps honest)."""
    from pertgnn_tpu import telemetry

    class Capture:
        def __init__(self):
            self.gauges = {}

        def gauge(self, name, value, **tags):
            self.gauges[name] = value

    cap = Capture()
    real = telemetry.get_bus
    telemetry.get_bus = lambda: cap
    try:
        result = driver.run_repo()
    finally:
        telemetry.get_bus = real
    assert cap.gauges["audit.programs"] == len(result.programs)
    assert cap.gauges["audit.violations"] == 0
    assert cap.gauges["audit.seconds"] > 0


def test_lens_local_unpinned_output_flagged():
    """The negative pin behind the lens/local coverage above: a program
    that returns per-node data WITHOUT the -inf pad pin is flagged
    (node-pad lanes reach an output the caller keeps), while the
    engine's actual shape — where(node_mask, local, -inf) — is clean.
    Keeps the 'padded rows provably unrankable' proof non-vacuous."""
    def unpinned(w, x, mask, idx):
        return (x * w).sum(-1)  # node-pad lanes carried out verbatim

    def pinned(w, x, mask, idx):
        return jnp.where(mask, (x * w).sum(-1), -jnp.inf)

    res = _audit([_serve_spec(unpinned, name="lens/local/unpinned")],
                 passes=["padding-taint"])
    assert not res.ok and "node" in res.new[0].message
    assert _audit([_serve_spec(pinned, name="lens/local/pinned")],
                  passes=["padding-taint"]).ok


# --- padding-taint -------------------------------------------------------


def test_taint_masked_pool_proves_clean():
    def step(w, x, mask, node_graph):
        v = (x * w).sum(-1)
        v = jnp.where(mask, v, 0.0)
        return jax.ops.segment_sum(v, node_graph, num_segments=G)

    assert _audit([_serve_spec(step)], ["padding-taint"]).ok


def test_taint_mask_multiply_proves_clean():
    def step(w, x, mask, node_graph):
        v = (x * w).sum(-1) * mask.astype(jnp.float32)
        return jax.ops.segment_sum(v, node_graph, num_segments=G)

    assert _audit([_serve_spec(step)], ["padding-taint"]).ok


def test_taint_unmasked_scatter_is_flagged():
    """The negative pin: drop the mask and the proof MUST fail —
    pad-node values would flow into real graph sums."""
    def step(w, x, mask, node_graph):
        v = (x * w).sum(-1)
        return jax.ops.segment_sum(v, node_graph, num_segments=G)

    res = _audit([_serve_spec(step)], ["padding-taint"])
    assert not res.ok
    assert any("node" in v.key for v in res.new)


def test_taint_unmasked_reduce_is_flagged():
    def step(w, x, mask, node_graph):
        return jnp.broadcast_to(x.sum(), (G,))

    res = _audit([_serve_spec(step)], ["padding-taint"])
    assert not res.ok


def test_taint_undiscarded_output_lanes_are_flagged():
    """A node-laned output whose pad lanes nobody discards leaks —
    the out_discard contract is load-bearing, not decoration."""
    def step(w, x, mask, node_graph):
        return (x * w).sum(-1)

    res = _audit([_serve_spec(step, out_discard=())], ["padding-taint"])
    assert not res.ok and any("leak" in v.key for v in res.new)
    # the same program is fine when the caller declares it slices the
    # node-pad tail off
    assert _audit([_serve_spec(step, out_discard=("node",))],
                  ["padding-taint"]).ok


def test_taint_audits_roled_train_programs_not_just_serve():
    """The negative pin behind the scale/sar_bucket_terms coverage: the
    padding-taint pass audits ANY program that declares invar roles —
    a TRAIN-tagged bucket-terms program whose loss sum drops the mask
    MUST be flagged (pad-graph labels would flow into the accumulated
    epoch gradient), and the masked shape proves clean. Keeps the
    gate's move from tag-based to role-based selection non-vacuous."""
    extra = (jax.ShapeDtypeStruct((G,), jnp.float32),
             jax.ShapeDtypeStruct((G,), jnp.bool_))
    roles = (Role(kind="data", cls="graph", path="y"),
             Role(kind="mask", cls="graph", path="graph_mask"))

    def unmasked(w, x, mask, node_graph, y, graph_mask):
        v = (x * w).sum(-1)
        pooled = jax.ops.segment_sum(
            v * mask.astype(jnp.float32), node_graph, num_segments=G)
        return jnp.abs(y - pooled).sum()  # pad-graph LABELS in the loss

    def masked(w, x, mask, node_graph, y, graph_mask):
        v = (x * w).sum(-1)
        pooled = jax.ops.segment_sum(
            v * mask.astype(jnp.float32), node_graph, num_segments=G)
        e = jnp.abs(y - pooled) * graph_mask.astype(jnp.float32)
        return e.sum()

    res = _audit([_serve_spec(unmasked, name="scale/bucket_unmasked",
                              tags=("train", "scale"), out_discard=(),
                              extra_avals=extra, extra_roles=roles)],
                 ["padding-taint"])
    assert not res.ok and any("graph" in v.key for v in res.new)
    clean = _serve_spec(
        masked, name="scale/bucket_masked", tags=("train", "scale"),
        out_discard=(), extra_avals=extra, extra_roles=roles)
    assert _audit([clean], ["padding-taint"]).ok


def test_taint_gather_route_then_mask_proves_clean():
    """The segment-attention shape: gather node values by (padded)
    edge routing indices, mask by edge_mask, scatter back to nodes,
    pool — the chain the real serve program runs."""
    E = 10
    extra = (jax.ShapeDtypeStruct((E,), jnp.int32),
             jax.ShapeDtypeStruct((E,), jnp.bool_))
    roles = (Role(kind="route", cls="edge", target="node",
                  path="receivers"),
             Role(kind="mask", cls="edge", path="edge_mask"))

    def step(w, x, mask, node_graph, receivers, edge_mask):
        v = (x * w).sum(-1)
        per_edge = v[receivers]
        per_edge = jnp.where(edge_mask, per_edge, 0.0)
        back = jax.ops.segment_sum(per_edge, receivers, num_segments=N)
        back = back * mask.astype(jnp.float32)
        return jax.ops.segment_sum(back, node_graph, num_segments=G)

    assert _audit([_serve_spec(step, extra_roles=roles,
                               extra_avals=extra)],
                  ["padding-taint"]).ok


def test_taint_gather_without_mask_is_flagged():
    E = 10
    extra = (jax.ShapeDtypeStruct((E,), jnp.int32),
             jax.ShapeDtypeStruct((E,), jnp.bool_))
    roles = (Role(kind="route", cls="edge", target="node",
                  path="receivers"),
             Role(kind="mask", cls="edge", path="edge_mask"))

    def step(w, x, mask, node_graph, receivers, edge_mask):
        v = (x * w).sum(-1)
        per_edge = v[receivers]  # pad edges gather arbitrary rows...
        # ...and are scattered back UNMASKED
        back = jax.ops.segment_sum(per_edge, receivers, num_segments=N)
        back = back * mask.astype(jnp.float32)
        return jax.ops.segment_sum(back, node_graph, num_segments=G)

    res = _audit([_serve_spec(step, extra_roles=roles,
                              extra_avals=extra)], ["padding-taint"])
    assert not res.ok


# --- dtype-flow ----------------------------------------------------------


def _bf16_spec(fn, name="serve/bf16/mini/rung0", extra_avals=()):
    avals = (jax.ShapeDtypeStruct((F, F), jnp.bfloat16),
             jax.ShapeDtypeStruct((N, F), jnp.bfloat16)) + extra_avals
    traced = jax.jit(fn).trace(*avals)
    return ProgramSpec(name=name, tags=frozenset({"serve", "bf16"}),
                       jaxpr=traced.jaxpr)


def test_dtype_bf16_matmul_clean_and_f32_flagged():
    clean = _bf16_spec(lambda w, x: x @ w)
    assert _audit([clean], ["dtype-flow"]).ok
    upcast = _bf16_spec(
        lambda w, x: x.astype(jnp.float32) @ w.astype(jnp.float32))
    res = _audit([upcast], ["dtype-flow"])
    assert not res.ok and "float32" in res.new[0].message


def test_dtype_dead_f32_matmul_not_flagged():
    """DCE first: an f32 matmul XLA would delete is not a finding."""
    def fn(w, x):
        _dead = x.astype(jnp.float32) @ w.astype(jnp.float32)
        return x @ w

    assert _audit([_bf16_spec(fn)], ["dtype-flow"]).ok


def _int8_spec(fn, name="serve/int8/mini/rung0"):
    avals = (jax.ShapeDtypeStruct((F, F), jnp.int8),
             jax.ShapeDtypeStruct((1, F), jnp.float32),
             jax.ShapeDtypeStruct((N, F), jnp.bfloat16))
    traced = jax.jit(fn).trace(*avals)
    return ProgramSpec(name=name, tags=frozenset({"serve", "int8"}),
                       jaxpr=traced.jaxpr)


def test_dtype_int8_single_dequant_clean():
    def fn(q, scale, x):
        w = q.astype(jnp.bfloat16) * scale.astype(jnp.bfloat16)
        return x @ w

    assert _audit([_int8_spec(fn)], ["dtype-flow"]).ok


def test_dtype_int8_double_dequant_flagged():
    def fn(q, scale, x):
        w1 = q.astype(jnp.bfloat16) * scale.astype(jnp.bfloat16)
        w2 = q.astype(jnp.bfloat16) + 1
        return x @ w1 + x @ w2

    res = _audit([_int8_spec(fn)], ["dtype-flow"])
    assert not res.ok and any("convert-count" in v.key for v in res.new)


def test_dtype_int8_wide_dequant_flagged():
    def fn(q, scale, x):
        w = q.astype(jnp.float32) * scale
        return (x.astype(jnp.float32) @ w).astype(jnp.bfloat16)

    res = _audit([_int8_spec(fn)], ["dtype-flow"])
    keys = {v.key.split("@")[0] for v in res.new}
    assert "int8-wide-dequant" in {k.split("@")[0] for k in keys} or \
        any(v.key.startswith("int8-wide-dequant") for v in res.new)


def test_dtype_int8_without_int8_leaves_flagged():
    """A program TAGGED int8 whose params were dequantized host-side
    defeats the tier's HBM promise."""
    avals = (jax.ShapeDtypeStruct((F, F), jnp.bfloat16),
             jax.ShapeDtypeStruct((N, F), jnp.bfloat16))
    traced = jax.jit(lambda w, x: x @ w).trace(*avals)
    spec = ProgramSpec(name="serve/int8/mini/rung0",
                       tags=frozenset({"serve", "int8"}),
                       jaxpr=traced.jaxpr)
    res = _audit([spec], ["dtype-flow"])
    assert not res.ok and res.new[0].key == "no-int8-leaves"


# --- donation ------------------------------------------------------------


def _donation_spec(donate: bool):
    state_aval = jax.ShapeDtypeStruct((64, 64), jnp.float32)  # 16 KiB
    batch_aval = jax.ShapeDtypeStruct((8, 64), jnp.float32)

    def step(state, batch):
        return state + batch.sum(0), batch.sum()

    jit_fn = jax.jit(step, donate_argnums=(0,) if donate else ())
    traced = jit_fn.trace(state_aval, batch_aval)
    return ProgramSpec(name=f"train/mini_{donate}",
                       tags=frozenset({"train"}), jaxpr=traced.jaxpr,
                       expect_donated_state=True, state_flat_count=1,
                       state_paths=("state",),
                       lower=lambda t=traced: t.lower())


def test_donation_donated_clean_undonated_flagged():
    assert _audit([_donation_spec(True)], ["donation"]).ok
    res = _audit([_donation_spec(False)], ["donation"])
    assert not res.ok
    assert res.new[0].key == "undonated-state"
    assert "donate_argnums" in res.new[0].message


# --- host-interop --------------------------------------------------------


def test_host_interop_callback_flagged_and_clean_passes():
    def clean(w, x):
        return x @ w

    def leaky(w, x):
        jax.debug.print("serving {}", x.sum())
        return x @ w

    avals = (jax.ShapeDtypeStruct((F, F), jnp.float32),
             jax.ShapeDtypeStruct((N, F), jnp.float32))
    mk = lambda fn, nm: ProgramSpec(
        name=nm, tags=frozenset({"serve", "f32"}),
        jaxpr=jax.jit(fn).trace(*avals).jaxpr)
    assert _audit([mk(clean, "serve/f32/clean/rung0")],
                  ["host-interop"]).ok
    res = _audit([mk(leaky, "serve/f32/leaky/rung0")],
                 ["host-interop"])
    assert not res.ok and "debug_callback" in res.new[0].key


# --- collective-audit ----------------------------------------------------


def _psum_spec(mesh_axes):
    from pertgnn_tpu.parallel.graph_shard import _shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

    def fn(x):
        return _shard_map(lambda s: jax.lax.psum(s, "data"), mesh=mesh,
                          in_specs=P("data"), out_specs=P())(x)

    traced = jax.jit(fn).trace(jax.ShapeDtypeStruct((8,), jnp.float32))
    return ProgramSpec(name="sharded/mini", tags=frozenset({"sharded"}),
                       jaxpr=traced.jaxpr, mesh_axes=mesh_axes)


def test_collective_axis_names_checked():
    assert _audit([_psum_spec(("data", "model"))],
                  ["collective-audit"]).ok
    res = _audit([_psum_spec(("x",))], ["collective-audit"])
    assert not res.ok
    assert any("data" in v.message for v in res.new)


def test_collective_merge_allreduce_wrong_mesh_flagged():
    """The negative pin behind the scale/allreduce coverage: the REAL
    sharded-merge statistics round, declared against a mesh that lacks
    its axis, MUST be flagged — and inside a program with no declared
    mesh at all (the single-host SAR step's contract) it must be
    flagged as a smuggled collective."""
    from pertgnn_tpu.parallel.mesh import make_mesh
    from pertgnn_tpu.parallel.scale import allreduce_fn

    mesh = make_mesh(data=2, model=1, devices=jax.devices()[:2])
    traced = jax.jit(allreduce_fn(mesh, "sum")).trace(
        jax.ShapeDtypeStruct((2, 16), jnp.int32))
    wrong = ProgramSpec(name="scale/allreduce_sum",
                        tags=frozenset({"sharded", "scale"}),
                        jaxpr=traced.jaxpr, mesh_axes=("rows",))
    res = _audit([wrong], ["collective-audit"])
    assert not res.ok
    smuggled = ProgramSpec(name="scale/sar_step_packed",
                           tags=frozenset({"train", "scale"}),
                           jaxpr=traced.jaxpr, mesh_axes=None)
    res = _audit([smuggled], ["collective-audit"])
    assert not res.ok
    assert any("no declared mesh" in v.message
               or "single-device" in v.message for v in res.new)


def test_collective_in_single_device_program_flagged():
    spec = _psum_spec(None)
    spec = ProgramSpec(name="serve/f32/smuggled/rung0",
                       tags=frozenset({"serve", "f32"}),
                       jaxpr=spec.jaxpr, mesh_axes=None)
    res = _audit([spec], ["collective-audit"])
    assert not res.ok
    assert any("no declared mesh" in v.message
               or "single-device" in v.message for v in res.new)


# --- driver / CLI contract -----------------------------------------------


def test_all_five_passes_registered():
    assert list(registry()) == ["padding-taint", "dtype-flow",
                                "donation", "host-interop",
                                "collective-audit"]


def test_driver_baseline_accepts_known_debt():
    def step(w, x, mask, node_graph):
        return jnp.broadcast_to(x.sum(), (G,))

    spec = _serve_spec(step)
    dirty = _audit([spec], ["padding-taint"])
    assert not dirty.ok
    triples = {(v.rule, v.path, v.key) for v in dirty.new}
    accepted = driver.run_passes([spec], ["padding-taint"],
                                 baseline=triples)
    assert accepted.ok and len(accepted.baselined) == len(dirty.new)


def test_driver_build_errors_are_findings():
    res = driver.run_passes([], build_errors=[("serve/gone",
                                               "TypeError: boom")])
    assert not res.ok
    assert res.new[0].rule == "driver" and "boom" in res.new[0].message


def test_cli_exit_codes_and_json(capsys):
    rc = cli_main(["not-a-pass"])
    assert rc == 2
    rc = cli_main(["--baseline", "/nonexistent/baseline.json"])
    assert rc == 2
    rc = cli_main(["host-interop", "--json",
                   "--programs", "serve/f32/segment/*"])
    out = capsys.readouterr().out
    doc = json.loads(out.strip().splitlines()[-1])
    assert rc == 0 and doc["ok"]
    assert all(p.startswith("serve/f32/segment/")
               for p in doc["programs"])


def test_console_launcher_resolves_sibling_tools(capsys):
    """The editable-install `graftaudit` entry point resolves the repo's
    tools/graftaudit (wheels must not squat a top-level `tools`
    namespace — same pattern as graftlint_cli)."""
    from pertgnn_tpu.graftaudit_cli import main as launcher

    assert launcher(["--list"]) == 0
    assert "padding-taint" in capsys.readouterr().out


# --- bench.py --gate refusal ---------------------------------------------


def test_bench_gate_refuses_audit_failing_tree(tmp_path, monkeypatch,
                                               capsys):
    import bench
    import tools.graftaudit as ga

    fake = driver.AuditResult(
        new=[driver.Violation(rule="padding-taint", path="serve/x",
                              line=0, message="pad leak")],
        baselined=[], allowed=[], elapsed_s=0.0,
        passes=["padding-taint"], programs=["serve/x"])
    monkeypatch.setattr(ga, "run_repo", lambda: fake)
    result = tmp_path / "result.json"
    result.write_text(json.dumps({"backend": "cpu", "value": 1.0,
                                  "attention_impl": "segment"}))
    rc = bench.gate_main([str(result)])
    out = capsys.readouterr().out
    assert rc == 1 and "graftaudit" in out and "pad leak" in out


def test_bench_gate_skip_audit_env_is_loud(monkeypatch, capsys):
    import bench

    monkeypatch.setenv("BENCH_GATE_SKIP_AUDIT", "1")
    assert bench._graftaudit_refusal() == []
    assert "WITHOUT the graftaudit check" in capsys.readouterr().err


def test_bench_gate_passes_clean_tree_through_audit(tmp_path, capsys):
    """End-to-end: a clean tree's gate runs lint AND audit and still
    reaches the throughput check (the in-process CPU path, so the
    audit's toy programs are the cached per-process build)."""
    import bench

    res = tmp_path / "result.json"
    res.write_text(json.dumps({"value": 2800.0, "backend": "cpu",
                               "attention_impl": "segment"}))
    rc = bench.gate_main([str(res)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and "gate" in out
