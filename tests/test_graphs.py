"""Graph-construction golden tests.

Hand-derived expectations pin the reference's semantics: sanitizer order
(misc.py:87-105), span compaction (misc.py:190-219), and the PERT 2k+1 stage
expansion + event-ordered edges (misc.py:221-302). The hand expansion for the
golden trace is worked through in comments.
"""

import numpy as np
import pandas as pd
import pytest

from pertgnn_tpu.graphs.construct import (
    build_pert_graph,
    build_span_graph,
    find_root,
    min_depth_from_root,
    sanitize_edges,
)


def _trace(rows):
    df = pd.DataFrame(
        rows, columns=["timestamp", "rpcid", "um", "rpctype", "dm",
                       "interface", "rt"])
    df["endTimestamp"] = df["timestamp"] + df["rt"].abs()
    return df


@pytest.fixture
def golden():
    # root=100 calls 1; 1 calls 2 and 3; 3 calls 4. One negative rt.
    return _trace([
        (0, 0, 100, 0, 1, 5, 100),
        (1, 1, 1, 1, 2, 6, 50),
        (2, 2, 1, 1, 3, 7, -30),
        (3, 3, 3, 2, 4, 8, 10),
    ])


class TestSanitizer:
    def test_self_loop_removed(self):
        df = _trace([(0, 0, 9, 0, 1, 0, 100), (1, 1, 1, 0, 1, 0, 10)])
        out = sanitize_edges(df, find_root(df))
        assert len(out) == 1

    def test_duplicate_rpcid_keeps_first(self):
        df = _trace([(0, 0, 9, 0, 1, 0, 100), (1, 7, 1, 0, 2, 1, 10),
                     (2, 7, 1, 0, 3, 2, 10)])
        out = sanitize_edges(df, find_root(df))
        assert set(out["dm"]) == {1, 2}

    def test_edge_into_root_removed(self):
        df = _trace([(0, 0, 9, 0, 1, 0, 100), (1, 1, 1, 0, 9, 1, 10)])
        out = sanitize_edges(df, find_root(df))
        assert (out["dm"] != 9).all()

    def test_umdm_dedup_keeps_last(self):
        df = _trace([(0, 0, 9, 0, 1, 0, 100), (1, 1, 1, 0, 2, 5, 10),
                     (2, 2, 1, 0, 2, 6, 20)])
        out = sanitize_edges(df, find_root(df))
        dup = out[(out.um == 1) & (out.dm == 2)]
        assert len(dup) == 1
        assert dup["interface"].iloc[0] == 6  # keep="last" (misc.py:97)

    def test_reverse_pair_keeps_first(self):
        df = _trace([(0, 0, 9, 0, 1, 0, 100), (1, 1, 1, 0, 2, 5, 10),
                     (2, 2, 2, 0, 1, 6, 20)])
        out = sanitize_edges(df, find_root(df))
        pair = out[(out.um.isin([1, 2])) & (out.dm.isin([1, 2]))]
        assert len(pair) == 1
        assert pair["um"].iloc[0] == 1  # first of the unordered pair kept


def test_root_detection_uses_abs_rt(golden):
    assert find_root(golden) == 100
    # negative but largest-|rt| row wins
    df = _trace([(0, 0, 7, 0, 1, 0, -500), (1, 1, 1, 0, 2, 1, 100)])
    assert find_root(df) == 7


def test_min_depth_bfs_handles_unreachable_and_deep():
    # chain 0->1->2, node 3 unreachable -> depth 0 (reference: inf -> 0)
    d = min_depth_from_root(4, np.array([0, 1]), np.array([1, 2]), 0)
    assert d.tolist() == [0, 1, 2, 0]
    # 10k-node chain must not blow the stack (reference's recursive DFS would)
    n = 10_000
    d = min_depth_from_root(n, np.arange(n - 1), np.arange(1, n), 0)
    assert d[-1] == n - 1


class TestSpanGolden:
    def test_structure(self, golden):
        g = build_span_graph(golden)
        # unique ms sorted: [1,2,3,4,100] -> 1:0 2:1 3:2 4:3 100:4
        assert g.ms_id.tolist() == [1, 2, 3, 4, 100]
        assert g.senders.tolist() == [4, 0, 0, 2]
        assert g.receivers.tolist() == [0, 1, 2, 3]
        assert g.edge_attr[:, 0].tolist() == [5, 6, 7, 8]   # interface
        assert g.edge_attr[:, 1].tolist() == [0, 1, 1, 2]   # rpctype
        # depths from root(100): 100=0, 1=1, 2=2, 3=2, 4=3, normalized by 3
        np.testing.assert_allclose(
            g.node_depth, np.array([1, 2, 2, 3, 0]) / 3.0, rtol=1e-6)


class TestPertGolden:
    def test_structure(self, golden):
        g = build_pert_graph(golden)
        # caller order by count desc, first-appearance ties:
        # um counts: 100->1, 1->2, 3->1  =>  [1(x2), 100, 3]
        # stages: 1 -> [0..4], 100 -> [5,6,7], 3 -> [8,9,10]
        # leaves {2,4} -> 2->11, 4->12
        assert g.num_nodes == 13
        assert g.ms_id.tolist() == [1] * 5 + [100] * 3 + [3] * 3 + [2, 4]

        edges = set(zip(g.senders.tolist(), g.receivers.tolist()))
        # intra-ms chains
        for chain in ([0, 1, 2, 3, 4], [5, 6, 7], [8, 9, 10]):
            for a, b in zip(chain, chain[1:]):
                assert (a, b) in edges
        # caller 1 events sorted by time:
        # (1,start,2) (2,start,3) (32,end,3) (51,end,2)
        assert (0, 11) in edges    # 1 calls 2 at slot 0
        assert (1, 8) in edges     # 1 calls 3 at slot 1
        assert (10, 3) in edges    # 3 returns into slot 3
        assert (11, 4) in edges    # 2 returns into slot 4
        # caller 3: call 4 then return
        assert (8, 12) in edges
        assert (12, 10) in edges
        # caller 100: call 1 (event i=0), return (event i=1 -> slot 2)
        assert (5, 0) in edges
        assert (4, 7) in edges

        # edge attrs: intra-ms edges are [0,0,1,1]
        attr = {(s, r): a for s, r, a in
                zip(g.senders.tolist(), g.receivers.tolist(),
                    g.edge_attr.tolist())}
        assert attr[(0, 1)] == [0, 0, 1, 1]
        assert attr[(0, 11)] == [6, 1, 1, 0]   # call edge carries iface/type
        assert attr[(11, 4)] == [0, 0, 0, 0]   # return edge zeroed features
        # total edges: intra 4+2+2=8, inter 2 per span * 4 spans = 8
        assert g.num_edges == 16

    def test_depth_root_is_first_stage_of_root(self, golden):
        g = build_pert_graph(golden)
        # root nid = stages[100][0] = 5 -> depth 0 -> normalized 0
        assert g.node_depth[5] == 0.0
        assert g.node_depth.max() == 1.0


def test_span_pert_consistency_on_synthetic(preprocessed):
    """Every runtime pattern builds valid span and PERT graphs."""
    from pertgnn_tpu.graphs.construct import build_runtime_graphs
    from pertgnn_tpu.ingest.assemble import assemble

    table = assemble(preprocessed)
    spans = build_runtime_graphs(preprocessed, table, "span")
    perts = build_runtime_graphs(preprocessed, table, "pert")
    assert set(spans) == set(perts) == set(table.runtime2trace)
    for rid, g in spans.items():
        assert g.senders.max(initial=-1) < g.num_nodes
        assert g.receivers.max(initial=-1) < g.num_nodes
        p = perts[rid]
        # PERT expansion is strictly larger than the span graph
        assert p.num_nodes >= g.num_nodes
        # PERT graphs are DAGs: BFS from root reaches nodes with finite depth;
        # verify acyclicity via topological sort
        indeg = np.zeros(p.num_nodes, dtype=int)
        np.add.at(indeg, p.receivers, 1)
        adj = [[] for _ in range(p.num_nodes)]
        for s, r in zip(p.senders, p.receivers):
            adj[s].append(r)
        stack = [i for i in range(p.num_nodes) if indeg[i] == 0]
        seen = 0
        while stack:
            v = stack.pop()
            seen += 1
            for w in adj[v]:
                indeg[w] -= 1
                if indeg[w] == 0:
                    stack.append(w)
        assert seen == p.num_nodes, f"cycle in PERT graph {rid}"


def test_root_sanitized_away_degrades_gracefully():
    """Duplicate rpcid on the entry row can drop every row mentioning the
    root; the reference KeyErrors (misc.py:204/311) — we emit zero depths."""
    # root row (max |rt|, min ts) shares an rpcid with an earlier row, so
    # rpcid dedup (keep="first") drops it and the root vanishes from the graph
    df = _trace([
        (0, 5, 1, 1, 2, 6, 50),
        (0, 5, 100, 0, 1, 5, 100),   # max |rt| & min ts -> root=100, dropped
        (1, 6, 1, 1, 3, 7, 20),
    ])
    g = build_span_graph(df)
    assert (g.node_depth == 0).all()
    p = build_pert_graph(df)
    assert (p.node_depth == 0).all()


def test_sanitize_traces_matches_per_trace(preprocessed):
    """Vectorized multi-trace sanitization == per-trace sanitize_edges."""
    from pertgnn_tpu.graphs.construct import sanitize_traces
    import pandas as pd

    spans = preprocessed.spans
    sanitized, roots = sanitize_traces(spans)
    for tid, grp in list(spans.groupby("traceid"))[:30]:
        root = find_root(grp)
        assert roots[tid] == root
        want = sanitize_edges(grp, root)
        got = sanitized[sanitized["traceid"] == tid]
        pd.testing.assert_frame_equal(got, want)


class TestNativeParity:
    def test_native_pert_matches_numpy(self, preprocessed):
        from pertgnn_tpu.graphs.construct import build_runtime_graphs
        from pertgnn_tpu.ingest.assemble import assemble
        from pertgnn_tpu.native import bindings

        if not bindings.available():
            pytest.skip("native library unavailable")
        table = assemble(preprocessed)
        py = build_runtime_graphs(preprocessed, table, "pert",
                                  use_native=False)
        nat = bindings.build_runtime_graphs(preprocessed, table, "pert")
        assert set(py) == set(nat)
        for rid in py:
            a, b = py[rid], nat[rid]
            assert a.num_nodes == b.num_nodes
            np.testing.assert_array_equal(a.senders, b.senders)
            np.testing.assert_array_equal(a.receivers, b.receivers)
            np.testing.assert_array_equal(a.edge_attr, b.edge_attr)
            np.testing.assert_array_equal(a.ms_id, b.ms_id)
            np.testing.assert_allclose(a.node_depth, b.node_depth, rtol=1e-6)

    def test_auto_path_falls_back_when_native_broken(self, preprocessed,
                                                     monkeypatch):
        """use_native=None must fall back to numpy when the loader fails;
        use_native=True must surface the error."""
        from pertgnn_tpu.graphs.construct import build_runtime_graphs
        from pertgnn_tpu.ingest.assemble import assemble
        from pertgnn_tpu.native import bindings

        def boom():
            raise OSError("corrupt .so")

        monkeypatch.setattr(bindings, "available", boom)
        table = assemble(preprocessed)
        auto = build_runtime_graphs(preprocessed, table, "pert")  # no raise
        assert len(auto) == len(table.runtime2trace)
        with pytest.raises(OSError):
            build_runtime_graphs(preprocessed, table, "pert", use_native=True)


class TestPrototype:
    """Legacy cluster-prototype capability (misc.py:23-49 semantics)."""

    def test_graph_union_weights_and_order(self):
        import pandas as pd
        from pertgnn_tpu.graphs.prototype import dag_prototype_from_cluster
        spans = pd.DataFrame({
            "um": [1, 1, 2, 1, 3, 2],
            "dm": [2, 2, 4, 3, 5, 4],
        })
        proto = dag_prototype_from_cluster(spans)
        got = {(int(s), int(r)): float(w) for s, r, w in
               zip(proto.senders, proto.receivers, proto.edge_weight)}
        assert got == {(1, 2): 2.0, (2, 4): 2.0, (1, 3): 1.0, (3, 5): 1.0}
        # count-descending ordering (value_counts semantics)
        assert list(proto.edge_weight) == sorted(proto.edge_weight,
                                                 reverse=True)

    def test_unsupported_merge_method_raises(self):
        import pandas as pd
        import pytest
        from pertgnn_tpu.graphs.prototype import dag_prototype_from_cluster
        with pytest.raises(ValueError):
            dag_prototype_from_cluster(
                pd.DataFrame({"um": [1], "dm": [2]}),
                merge_method="graph_dtw")

    def test_merge_label_spaces(self):
        import numpy as np
        from pertgnn_tpu.graphs.prototype import merge_label_spaces
        assert merge_label_spaces(np.array([0, 2, 1]), 4) == 7


def test_span_edge_durations_carried():
    """Span builder persists |rt| per kept edge (the reference computes but
    drops these, misc.py:183-186); pert graphs carry None -> zeros."""
    import numpy as np
    import pandas as pd
    from pertgnn_tpu.graphs.construct import build_pert_graph, build_span_graph
    df = pd.DataFrame({
        "traceid": [0, 0, 0],
        "rpcid": [0, 1, 2],
        "um": [10, 10, 11],
        "dm": [11, 12, 13],
        "interface": [0, 1, 2],
        "rpctype": [0, 0, 0],
        "timestamp": [0.0, 1.0, 2.0],
        "rt": [100.0, -40.0, 30.0],
    })
    df["endTimestamp"] = df["timestamp"] + df["rt"].abs()
    span = build_span_graph(df)
    assert span.edge_durations is not None
    assert sorted(span.edge_durations.tolist()) == [30.0, 40.0, 100.0]
    pert = build_pert_graph(df)
    assert pert.edge_durations is None
