"""graftwire data plane: the binary codec and the shared-memory ring.

Codec tests pin STRUCT-level round-trips (decode(encode(x)) == x with
float bit-equality) and the refusal contract: a truncated, corrupt, or
version-skewed frame raises WireFormatError — never anything else, and
never a crash. Ring tests pin the SPSC protocol: wrap-around,
full-ring backpressure, torn-write detection, and the doorbell's
peer-death/timeout surfacing. The hypothesis property is gated the
repo's usual way (importorskip) so environments without hypothesis
still run every example-based case.
"""

import struct
import threading
import time

import pytest

from pertgnn_tpu.fleet import shmring, wire

# --- codec: request frames ------------------------------------------------


def test_request_roundtrip_minimal():
    buf = wire.encode_request([1, 2, 3], [10, 20, 30])
    assert wire.decode_request(buf) == {"entries": [1, 2, 3],
                                        "ts_buckets": [10, 20, 30]}


def test_request_omit_when_default_sections_absent():
    """All-default metadata must not appear in the decoded body at all
    (the same omit-when-default contract as the JSON wire)."""
    buf = wire.encode_request([7], [8], trace=[None], slo=[None],
                              dg=[False], lens=[None])
    assert wire.decode_request(buf) == {"entries": [7],
                                        "ts_buckets": [8]}


def test_request_roundtrip_full_metadata():
    trace = [{"tid": "t1", "psid": "s1"}, None]
    slo = ["critical", None]
    dg = [False, True]
    lens = [None, {"kind": "whatif", "edits": [[1, 2]]}]
    buf = wire.encode_request([4, 5], [1, 1], trace=trace, slo=slo,
                              dg=dg, lens=lens)
    got = wire.decode_request(buf)
    assert got == {"entries": [4, 5], "ts_buckets": [1, 1],
                   "trace": trace, "slo": slo, "dg": dg, "lens": lens}


def test_request_dg_bitmask_is_compact():
    """9 flags fit 2 mask bytes (count u32 + LSB-first bits)."""
    dg = [True] + [False] * 7 + [True]
    buf = wire.encode_request(list(range(9)), [0] * 9, dg=dg)
    assert wire.decode_request(buf)["dg"] == dg


# --- codec: response frames -----------------------------------------------


def test_response_roundtrip_scalar_vector_error_attr():
    rows = [
        {"pred": 1.5},
        {"pred": [0.25, 0.5, 0.75]},                     # f32-exact
        {"error": "Shed", "message": "class best_effort shed"},
        {"pred": [0.1, 0.2], "attr": [{"rank": 1, "score": 0.5}]},
    ]
    got = wire.decode_response(wire.encode_response(rows))
    assert got == rows
    # float equality above is STRUCT-level: 0.1 does not survive f32,
    # so the codec must have chosen the f64 block for that row
    assert got[3]["pred"][0] == 0.1


def test_response_vector_width_narrows_only_when_exact():
    exact = [float(struct.unpack("<f", struct.pack("<f", v))[0])
             for v in (1.1, 2.2, 3.3)]
    buf_exact = wire.encode_response([{"pred": exact}])
    buf_wide = wire.encode_response([{"pred": [1.1, 2.2, 3.3]}])
    assert len(buf_exact) < len(buf_wide)
    assert wire.decode_response(buf_exact) == [{"pred": exact}]
    assert wire.decode_response(buf_wide) == [{"pred": [1.1, 2.2, 3.3]}]


def test_response_roundtrip_property():
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    finite = st.floats(allow_nan=False, allow_infinity=False)
    row = st.one_of(
        st.fixed_dictionaries({"pred": finite}),
        st.fixed_dictionaries({"pred": st.lists(finite, max_size=8)}),
        st.fixed_dictionaries({"error": st.text(max_size=20),
                               "message": st.text(max_size=40)}))

    @hyp.given(st.lists(row, max_size=16))
    @hyp.settings(deadline=None, max_examples=200)
    def check(rows):
        assert wire.decode_response(wire.encode_response(rows)) == rows

    check()


def test_request_roundtrip_property():
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    i64 = st.integers(min_value=-2**63, max_value=2**63 - 1)

    @hyp.given(st.lists(i64, max_size=32), st.lists(i64, max_size=32))
    @hyp.settings(deadline=None, max_examples=200)
    def check(entries, ts):
        got = wire.decode_request(wire.encode_request(entries, ts))
        assert got == {"entries": entries, "ts_buckets": ts}

    check()


# --- codec: refusals, truncation, corruption, skew ------------------------


def test_every_truncation_is_a_typed_refusal():
    """EVERY proper prefix of a valid frame must raise WireFormatError
    — no IndexError, no struct.error, no silent partial decode."""
    buf = wire.encode_response([{"pred": 1.0, "cache_hit": True},
                                {"pred": [1.0, 2.0]},
                                {"error": "QueueFull", "message": "x"}])
    for cut in range(len(buf)):
        with pytest.raises(wire.WireFormatError):
            wire.decode_response(buf[:cut])
    req = wire.encode_request([1, 2], [3, 4], dg=[True, False])
    for cut in range(len(req)):
        with pytest.raises(wire.WireFormatError):
            wire.decode_request(req[:cut])


def test_bad_magic_and_wrong_kind_refused():
    buf = wire.encode_request([1], [2])
    with pytest.raises(wire.WireFormatError, match="magic"):
        wire.decode_request(b"XX" + buf[2:])
    # a request frame handed to the response decoder is a kind error
    with pytest.raises(wire.WireFormatError, match="kind"):
        wire.decode_response(buf)


def test_version_skew_refused():
    buf = bytearray(wire.encode_request([1], [2]))
    buf[2] = wire.WIRE_VERSION + 1
    with pytest.raises(wire.WireFormatError, match="version skew"):
        wire.decode_request(bytes(buf))


def test_trailing_garbage_refused():
    buf = wire.encode_request([1], [2])
    with pytest.raises(wire.WireFormatError, match="length"):
        wire.decode_request(buf + b"\x00")


def test_duplicate_section_refused():
    sec = wire._section(wire._TAG_ENTRIES, wire._pack_i64s([1]))
    ts = wire._section(wire._TAG_TS, wire._pack_i64s([2]))
    frame = wire._frame(wire.KIND_REQUEST, [sec, ts, sec])
    with pytest.raises(wire.WireFormatError, match="duplicate"):
        wire.decode_request(frame)


def test_vector_count_mismatch_refused():
    """The vectors section's nvec prefix must equal the number of
    vector rowkinds, and the section must be fully consumed — a wrong
    count or trailing garbage is a typed refusal, same strictness as
    every other section."""
    rowkind = wire._section(
        wire._TAG_ROWKIND, struct.pack("<I", 1) + bytes([wire._ROW_VECTOR]))
    block = struct.pack("<BI", 4, 1) + struct.pack("<f", 1.0)
    bad_count = wire._frame(wire.KIND_RESPONSE, [
        rowkind,
        wire._section(wire._TAG_VECTORS, struct.pack("<I", 2) + block)])
    with pytest.raises(wire.WireFormatError, match="vector"):
        wire.decode_response(bad_count)
    bad_tail = wire._frame(wire.KIND_RESPONSE, [
        rowkind,
        wire._section(wire._TAG_VECTORS,
                      struct.pack("<I", 1) + block + b"\x00")])
    with pytest.raises(wire.WireFormatError, match="trailing"):
        wire.decode_response(bad_tail)


def test_cache_hit_flags_roundtrip_and_omit_when_default():
    """The 0x15 cache_hit bitmask (fleet/memo.py hits): flags survive
    the round trip on any row kind, and an all-miss frame carries no
    section at all — pre-memo peers and cold traffic pay zero bytes."""
    rows = [
        {"pred": 1.5, "cache_hit": True},
        {"pred": [0.25, 0.5, 0.75]},
        {"error": "Shed", "message": "x"},
        {"pred": [0.1, 0.2], "attr": [{"rank": 1, "score": 0.5}],
         "cache_hit": True},
    ]
    assert wire.decode_response(wire.encode_response(rows)) == rows
    plain = [{"pred": 1.5}, {"error": "Shed", "message": "x"}]
    buf = wire.encode_response(plain)
    assert wire.decode_response(buf) == plain
    flagged = wire.encode_response(
        [{**plain[0], "cache_hit": True}, plain[1]])
    assert len(buf) < len(flagged)      # the section was truly absent


def test_cache_hit_count_mismatch_refused():
    rowkind = wire._section(
        wire._TAG_ROWKIND,
        struct.pack("<I", 1) + bytes([wire._ROW_SCALAR]))
    scalars = wire._section(wire._TAG_SCALARS,
                            struct.pack("<d", 1.0))
    bad = wire._frame(wire.KIND_RESPONSE, [
        rowkind, scalars,
        wire._section(wire._TAG_CACHE,
                      struct.pack("<I", 2) + b"\x03")])
    with pytest.raises(wire.WireFormatError, match="flag count"):
        wire.decode_response(bad)


def test_cache_hit_mask_length_mismatch_refused():
    rowkind = wire._section(
        wire._TAG_ROWKIND,
        struct.pack("<I", 1) + bytes([wire._ROW_SCALAR]))
    scalars = wire._section(wire._TAG_SCALARS,
                            struct.pack("<d", 1.0))
    for mask in (b"", b"\x01\x00"):      # short and long
        bad = wire._frame(wire.KIND_RESPONSE, [
            rowkind, scalars,
            wire._section(wire._TAG_CACHE,
                          struct.pack("<I", 1) + mask)])
        with pytest.raises(wire.WireFormatError,
                           match="mask bytes|truncated"):
            wire.decode_response(bad)


def test_refusal_frame_raises_wire_refusal():
    buf = wire.encode_refusal("WireFormatError", "version skew v9")
    with pytest.raises(wire.WireRefusal, match="version skew v9"):
        wire.decode_response(buf)
    with pytest.raises(wire.WireRefusal):
        wire.decode_request(buf)
    # and WireRefusal IS a WireFormatError: one except arm suffices
    assert issubclass(wire.WireRefusal, wire.WireFormatError)


# --- shm ring: SPSC protocol ----------------------------------------------


@pytest.fixture
def ring():
    r = shmring.ShmRing.create(slots=4, slot_bytes=64)
    yield r
    r.close()


def test_ring_wraparound_preserves_frames(ring):
    """20 frames through a 4-slot ring — every wrap lap intact."""
    for i in range(20):
        payload = f"frame-{i}".encode() * 2
        assert ring.try_push(payload)
        assert ring.try_pop() == payload
    assert ring.try_pop() is None


def test_ring_full_backpressure(ring):
    for i in range(ring.slots):
        assert ring.try_push(f"p{i}".encode())
    assert not ring.try_push(b"overflow")     # consumer owns the oldest
    assert ring.try_pop() == b"p0"
    assert ring.try_push(b"now-it-fits")
    got = [ring.try_pop() for _ in range(ring.slots)]
    assert got == [b"p1", b"p2", b"p3", b"now-it-fits"]


def test_ring_oversize_frame_refused(ring):
    with pytest.raises(shmring.RingFrameTooLarge):
        ring.try_push(b"x" * (ring.payload_max + 1))
    assert ring.try_pop() is None             # nothing was committed


def test_ring_torn_write_detected(ring):
    """A stamp from the future means the producer lapped an unconsumed
    slot — the consumer must refuse the ring, not return garbage."""
    assert ring.try_push(b"ok")
    off = ring._slot_off(1)
    ring._seq_write(off, 1 + ring.slots)      # producer lap, mid-copy
    with pytest.raises(shmring.RingTornWrite):
        ring.try_pop()


def test_ring_payload_bit_rot_detected(ring):
    """A flipped payload BIT in shared memory (bad DIMM, a stray write
    from a buggy peer) passes every stamp check — only the per-frame
    CRC32C can catch it. Consume must reject the frame, not deliver
    silently corrupt bytes to the batcher."""
    from pertgnn_tpu import telemetry

    class _CountingBus(telemetry.NoopBus):
        def __init__(self):
            self.counts: dict[str, int] = {}

        def counter(self, name, value=1, *, level=1, **tags):
            self.counts[name] = self.counts.get(name, 0) + 1

    assert ring.try_push(b"payload-under-test")
    # first frame is seq 1; its payload starts after the slot header
    payload_off = ring._slot_off(1) + shmring._SLOT_HDR
    ring._shm.buf[payload_off + 3] ^= 0x10
    bus = _CountingBus()
    prev = telemetry.set_bus(bus)
    try:
        with pytest.raises(shmring.RingTornWrite) as ei:
            ring.try_pop()
    finally:
        telemetry.set_bus(prev)
    assert getattr(ei.value, "crc_mismatch", False)
    assert "crc" in str(ei.value)
    assert bus.counts.get("transport.crc_rejects") == 1


def test_ring_attach_version_skew_refused():
    r = shmring.ShmRing.create(slots=2, slot_bytes=64)
    try:
        name = r.name
        struct.pack_into("<I", r._shm.buf, 4, shmring.RING_VERSION + 1)
        with pytest.raises(shmring.RingError, match="version skew"):
            shmring.ShmRing.attach(name)
    finally:
        r.close()


def test_ring_attach_gone_segment_is_peer_death():
    with pytest.raises(shmring.RingPeerDead):
        shmring.ShmRing.attach("graftwire-no-such-segment")


# --- shm ring: server/client round trips ----------------------------------


def test_ring_client_server_roundtrip():
    server = shmring.RingServer(lambda b: b.upper(), slots=4,
                                slot_bytes=256)
    client = None
    try:
        client = shmring.RingClient(server.advertisement())
        for i in range(25):                   # several wrap laps
            msg = f"frame-{i}".encode()
            assert client.call(msg, timeout_s=5.0) == msg.upper()
    finally:
        if client is not None:
            client.close()
        server.close()


def test_ring_call_timeout_is_bounded():
    """A wedged handler surfaces as RingTimeout at the DEADLINE — the
    transport maps it to the lost-worker path; nothing spins."""
    release = threading.Event()

    def slow(b):
        release.wait(5.0)
        return b

    server = shmring.RingServer(slow, slots=2, slot_bytes=128)
    client = None
    try:
        client = shmring.RingClient(server.advertisement())
        t0 = time.monotonic()
        with pytest.raises(shmring.RingTimeout):
            client.call(b"x", timeout_s=0.3)
        assert time.monotonic() - t0 < 3.0
    finally:
        release.set()
        if client is not None:
            client.close()
        server.close()


def test_ring_server_death_surfaces_as_peer_dead():
    server = shmring.RingServer(lambda b: b, slots=2, slot_bytes=128)
    client = shmring.RingClient(server.advertisement())
    try:
        assert client.call(b"alive", timeout_s=5.0) == b"alive"
        server.close()                        # the worker is SIGKILLed
        with pytest.raises((shmring.RingPeerDead, shmring.RingTimeout)):
            client.call(b"anyone-there", timeout_s=1.0)
    finally:
        client.close()


def test_reattached_client_rejects_stale_response():
    """A response the worker pushes AFTER a timed-out client was
    dropped must never be accepted by a re-attached client: the
    correlation id is the request ring's shm-persistent sequence
    number, so the stale frame always mismatches and is discarded —
    never returned as another batch's predictions."""
    release = threading.Event()
    slow_once = [True]

    def handle(b):
        if slow_once[0]:
            slow_once[0] = False
            release.wait(5.0)                 # wedge the FIRST call
        return b"echo:" + b

    server = shmring.RingServer(handle, slots=4, slot_bytes=128)
    first = shmring.RingClient(server.advertisement())
    try:
        with pytest.raises(shmring.RingTimeout):
            first.call(b"abandoned", timeout_s=0.3)
        first.close()                         # transport drops the ring
        release.set()                         # …the worker answers late
        deadline = time.monotonic() + 5.0
        while (server._rsp._load_ctr(shmring._PRODUCED_OFF) == 0
               and time.monotonic() < deadline):
            time.sleep(0.005)                 # stale frame is IN the ring
        fresh = shmring.RingClient(server.advertisement())
        try:
            assert fresh.call(b"fresh", timeout_s=5.0) == b"echo:fresh"
        finally:
            fresh.close()
    finally:
        release.set()
        server.close()


def test_fresh_attach_serviced_past_stale_connection():
    """A doorbell connection nobody closed (an abandoned client) must
    not starve a newly attached client — the server selects over ALL
    live connections, so the fresh client's calls ride the bell, not
    the 0.25s poll fallback."""
    server = shmring.RingServer(lambda b: b, slots=4, slot_bytes=128)
    stale = shmring.RingClient(server.advertisement())
    fresh = None
    try:
        assert stale.call(b"once", timeout_s=5.0) == b"once"
        # stale's bell conn stays open; a second client attaches
        fresh = shmring.RingClient(server.advertisement())
        t0 = time.monotonic()
        for i in range(5):
            msg = f"m{i}".encode()
            assert fresh.call(msg, timeout_s=5.0) == msg
        # bell-driven round trips are sub-millisecond; the old
        # one-connection accept loop cost ~0.25s/call via the poll
        assert time.monotonic() - t0 < 1.0
    finally:
        stale.close()
        if fresh is not None:
            fresh.close()
        server.close()
