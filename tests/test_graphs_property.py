"""Property-based (hypothesis) invariants for the graph layer.

The golden tests in test_graphs.py pin exact reference semantics on
hand-built traces; these fuzz the same functions over random messy traces
(self-loops, duplicate rpcids, reverse pairs, negative rt, timestamp ties)
and assert the structural invariants that must hold for EVERY input:

- sanitizer (misc.py:87-105 semantics): idempotent; output free of
  self-loops, edges into the root, duplicate (um, dm) and duplicate
  unordered pairs;
- PERT builder (misc.py:221-302 semantics): the 2k+1 stage arithmetic,
  the edge-count law E = sum(2k) + 2*|sanitized|, index validity, the
  attr schema, and cycle-safety. NOTE: the PERT graph is NOT always a
  DAG — a callee with multiple callers shares one stage chain, and
  call/return edges through it can close a cycle (fuzzing found concrete
  4-row examples; see test_pert_can_be_cyclic_and_is_handled). That
  matches the reference, which disabled its max-depth DFS "due to
  cycles" (misc.py:119-134); min-depth BFS and the attention model are
  cycle-safe, which is what we assert instead;
- span builder (misc.py:190-219 semantics): node compaction and the
  1-edge-per-sanitized-row law.
"""

import numpy as np
import pandas as pd
import pytest

pytest.importorskip(
    "hypothesis",
    reason="property tests need the hypothesis dev extra "
           "(pip install -e .[dev])")
from hypothesis import assume, given, settings
from hypothesis import strategies as st

from pertgnn_tpu.graphs.construct import (
    build_pert_graph,
    build_span_graph,
    find_root,
    sanitize_edges,
)

# A random trace: rows of (timestamp, rpcid, um, rpctype, dm, interface, rt)
# over a small id universe so collisions (dup rpcid, reverse pairs,
# self-loops) actually happen.
_row = st.tuples(
    st.integers(0, 20),        # timestamp (ties likely)
    st.integers(0, 6),         # rpcid (duplicates likely)
    st.integers(0, 5),         # um
    st.integers(0, 3),         # rpctype
    st.integers(0, 5),         # dm (may equal um -> self-loop)
    st.integers(0, 9),         # interface
    st.integers(-100, 200).filter(lambda v: v != 0),  # rt (negatives seen)
)
_traces = st.lists(_row, min_size=1, max_size=12)


def _df(rows):
    df = pd.DataFrame(rows, columns=["timestamp", "rpcid", "um", "rpctype",
                                     "dm", "interface", "rt"])
    df["endTimestamp"] = df["timestamp"] + df["rt"].abs()
    return df


def _rooted(df):
    """find_root's precondition (guaranteed by entry filtering for every
    trace that reaches graph construction — see its docstring): some row
    has BOTH the min timestamp and the max |rt|."""
    abs_rt = df["rt"].abs()
    return bool(((abs_rt == abs_rt.max())
                 & (df["timestamp"] == df["timestamp"].min())).any())


def _is_dag(num_nodes: int, senders: np.ndarray,
            receivers: np.ndarray) -> bool:
    """Kahn's algorithm: all nodes peel off iff acyclic."""
    indeg = np.zeros(num_nodes, dtype=np.int64)
    np.add.at(indeg, receivers, 1)
    adj: list[list[int]] = [[] for _ in range(num_nodes)]
    for s, r in zip(senders.tolist(), receivers.tolist()):
        adj[s].append(r)
    stack = [i for i in range(num_nodes) if indeg[i] == 0]
    seen = 0
    while stack:
        n = stack.pop()
        seen += 1
        for m in adj[n]:
            indeg[m] -= 1
            if indeg[m] == 0:
                stack.append(m)
    return seen == num_nodes


@settings(max_examples=200, deadline=None)
@given(_traces)
def test_sanitizer_invariants(rows):
    df = _df(rows)
    assume(_rooted(df))
    root = find_root(df)
    out = sanitize_edges(df, root)
    # no self-loops, nothing back into the root
    assert (out["um"] != out["dm"]).all()
    assert (out["dm"] != root).all()
    # (um, dm) unique AND unordered pairs unique
    assert not out.duplicated(subset=["um", "dm"]).any()
    pairs = np.sort(out[["um", "dm"]].to_numpy(), axis=1)
    assert len(np.unique(pairs, axis=0)) == len(out)
    # idempotent: a clean trace passes through unchanged
    again = sanitize_edges(out, root)
    pd.testing.assert_frame_equal(again, out)


@settings(max_examples=200, deadline=None)
@given(_traces)
def test_pert_structural_laws(rows):
    df = _df(rows)
    assume(_rooted(df))
    root = find_root(df)
    san = sanitize_edges(df, root)
    if len(san) == 0:
        return  # pipeline never builds graphs for empty traces
    g = build_pert_graph(df, sanitized=san, root=root)

    um = san["um"].to_numpy()
    dm = san["dm"].to_numpy()
    callers, counts = np.unique(um, return_counts=True)
    leaves = sorted(set(dm.tolist()) - set(um.tolist()))
    # 2k+1 stages per caller, 1 node per pure leaf
    assert g.num_nodes == int((2 * counts + 1).sum()) + len(leaves)
    for ms, k in zip(callers.tolist(), counts.tolist()):
        assert int((g.ms_id == ms).sum()) == 2 * k + 1
    for leaf in leaves:
        assert int((g.ms_id == leaf).sum()) == 1
    # E = intra chains sum(2k) + (call + return) per sanitized edge
    assert g.num_edges == int((2 * counts).sum()) + 2 * len(san)
    # indices valid; attr schema [iface, rpctype, call_ind, same_ms_ind]
    assert g.senders.min() >= 0 and g.senders.max() < g.num_nodes
    assert g.receivers.min() >= 0 and g.receivers.max() < g.num_nodes
    assert g.edge_attr.shape == (g.num_edges, 4)
    assert set(np.unique(g.edge_attr[:, 2])) <= {0, 1}
    assert set(np.unique(g.edge_attr[:, 3])) <= {0, 1}
    # same-ms chain edges are exactly the intra-stage edges, and they
    # always step forward (cycles, when they occur, come from call/return
    # edges through shared multi-caller stage chains — allowed, see module
    # docstring; the builder and BFS must stay well-defined regardless)
    chain = g.edge_attr[:, 3] == 1
    assert int(chain.sum()) == int((2 * counts).sum())
    assert (g.senders[chain] < g.receivers[chain]).all()
    # depth normalized into [0, 1] — finite even on cyclic graphs
    assert np.isfinite(g.node_depth).all()
    assert g.node_depth.min() >= 0.0 and g.node_depth.max() <= 1.0


def test_pert_can_be_cyclic_and_is_handled():
    """Regression (found by fuzzing): a multi-caller sanitized trace whose
    PERT expansion contains a cycle. The reference produces cycles too
    (its max-depth DFS is disabled "due to cycles", misc.py:119-134);
    what we pin is that construction, the structural laws, and the
    min-depth BFS all stay well-defined on it."""
    rows = [(0, 0, 2, 0, 1, 0, 1), (1, 1, 0, 0, 2, 0, 2),
            (0, 2, 3, 0, 2, 0, 5), (4, 3, 1, 0, 1, 0, -3),
            (3, 4, 0, 0, 1, 0, 2)]
    df = _df(rows)
    assert _rooted(df)
    root = find_root(df)
    san = sanitize_edges(df, root)
    g = build_pert_graph(df, sanitized=san, root=root)
    assert not _is_dag(g.num_nodes, g.senders, g.receivers)  # genuinely cyclic
    counts = np.unique(san["um"].to_numpy(), return_counts=True)[1]
    assert g.num_edges == int((2 * counts).sum()) + 2 * len(san)
    assert np.isfinite(g.node_depth).all()
    assert 0.0 <= g.node_depth.min() and g.node_depth.max() <= 1.0


_sizes = st.lists(st.tuples(st.integers(1, 9), st.integers(0, 14)),
                  min_size=0, max_size=60)
_budget = st.tuples(st.integers(1, 7),      # max_graphs
                    st.integers(9, 40),     # max_nodes (>= biggest example)
                    st.integers(14, 60))    # max_edges


@settings(max_examples=200, deadline=None)
@given(_sizes, _budget)
def test_assign_batches_greedy_laws(sizes, budget_tuple):
    """Fuzz the vectorized greedy packer (batching/arena.py) against the
    scalar greedy rule and its invariants: every example exactly once, in
    order, budgets never exceeded, every non-final batch full (adding the
    next example would overflow some budget)."""
    from pertgnn_tpu.batching.arena import assign_batches
    from pertgnn_tpu.batching.pack import BatchBudget

    budget = BatchBudget(*budget_tuple)
    nc = np.array([s[0] for s in sizes], dtype=np.int64)
    ec = np.array([s[1] for s in sizes], dtype=np.int64)
    bi, gs, no, eo = assign_batches(nc, ec, budget)
    assert len(bi) == len(nc)
    if len(nc) == 0:
        return
    # order-preserving assignment: batch ids non-decreasing, slots count up
    assert (np.diff(bi) >= 0).all()
    for b in np.unique(bi):
        m = bi == b
        assert (gs[m] == np.arange(int(m.sum()))).all()
        # offsets are the within-batch cumsums
        np.testing.assert_array_equal(
            no[m], np.concatenate([[0], np.cumsum(nc[m])[:-1]]))
        np.testing.assert_array_equal(
            eo[m], np.concatenate([[0], np.cumsum(ec[m])[:-1]]))
        # budgets respected
        assert m.sum() <= budget.max_graphs
        assert nc[m].sum() <= budget.max_nodes
        assert ec[m].sum() <= budget.max_edges
    # greedy maximality: each batch boundary was forced by SOME budget
    starts = np.flatnonzero(np.diff(np.concatenate([[-1], bi])))
    for s in starts[1:]:
        m = bi == bi[s] - 1
        assert (m.sum() + 1 > budget.max_graphs
                or nc[m].sum() + nc[s] > budget.max_nodes
                or ec[m].sum() + ec[s] > budget.max_edges)


@settings(max_examples=200, deadline=None)
@given(_traces)
def test_span_structural_laws(rows):
    df = _df(rows)
    assume(_rooted(df))
    root = find_root(df)
    san = sanitize_edges(df, root)
    if len(san) == 0:
        return
    g = build_span_graph(df, sanitized=san, root=root)
    # compaction: nodes = unique ms among sanitized endpoints
    uniq = np.unique(np.concatenate([san["um"].to_numpy(),
                                     san["dm"].to_numpy()]))
    assert g.num_nodes == len(uniq)
    assert set(g.ms_id.tolist()) == set(uniq.tolist())
    # one edge per sanitized row, in range, attrs [iface, rpctype]
    assert g.num_edges == len(san)
    assert g.senders.max() < g.num_nodes and g.receivers.max() < g.num_nodes
    assert g.edge_attr.shape == (g.num_edges, 2)
    # carried durations = |rt| per row (dead-output capability, SURVEY §2.3)
    np.testing.assert_allclose(g.edge_durations,
                               san["rt"].abs().to_numpy(np.float32))
    assert g.node_depth.min() >= 0.0 and g.node_depth.max() <= 1.0
