"""Wedge-resilient bench capture: partial persistence + finalizer laws.

The axon relay flaps on minute timescales; bench.py therefore flushes every
measured window to a partial file and `--finalize-partial` promotes >=3
salvaged fit windows into the pinned result (see bench.py module comment).
This machinery guards the round's headline measurement, so its promotion /
no-downgrade / orphan-fallback rules are pinned here against tmp paths.
"""

import json
import os
import time

import pytest

import bench


@pytest.fixture
def paths(tmp_path, monkeypatch):
    partial = tmp_path / "partial.json"
    orphan = tmp_path / "partial.json.orphan"
    pin = tmp_path / "pin.json"
    monkeypatch.setattr(bench, "_PARTIAL", str(partial))
    monkeypatch.setattr(bench, "_ORPHAN", str(orphan))
    monkeypatch.setattr(bench, "_PIN", str(pin))
    # isolate the graftprobe journal too: the finalizer folds it in as a
    # salvage candidate (ISSUE 17) and must not see a real repo journal
    monkeypatch.setattr(bench, "_JOURNAL", str(tmp_path / "journal.jsonl"))
    return partial, orphan, pin


def _partial_payload(n_windows, backend="tpu", commit="cafe01", **extra):
    d = {
        "phase": "baseline_done", "commit": commit, "dirty_worktree": False,
        "traces_per_entry": 60, "backend": backend,
        "backend_fallback": False, "train_graphs_per_epoch": 478,
        "flops_per_graph": 3_162_933.0, "bytes_per_graph": 1_209_394.0,
        "peak_flops_per_chip": 197e12, "peak_hbm_bytes_per_s": 819e9,
        "baseline_torch_cpu_graphs_per_s": 700.0,
        "fit_windows": [15_000.0 + i for i in range(n_windows)],
        "ceiling_windows": [23_000.0] * max(n_windows - 1, 0),
        "compact_windows": [], "updated_unix_time": time.time(),
    }
    d.update(extra)
    return d


def test_update_partial_merges_and_survives(paths):
    partial, _, _ = paths
    bench._update_partial(phase="workload_built", commit="abc")
    bench._update_partial(fit_windows=[1.0, 2.0])
    d = json.loads(partial.read_text())
    assert d["commit"] == "abc" and d["fit_windows"] == [1.0, 2.0]
    assert "updated_unix_time" in d


def test_finalize_declines_below_min_windows(paths, capsys):
    partial, _, pin = paths
    partial.write_text(json.dumps(
        _partial_payload(bench._MIN_FIT_WINDOWS - 1)))
    assert bench.finalize_partial() == 1
    assert not pin.exists()
    assert "not promoting" in capsys.readouterr().out


def test_finalize_promotes_with_recorded_peaks_and_commit(paths, capsys):
    partial, _, pin = paths
    partial.write_text(json.dumps(_partial_payload(5)))
    assert bench.finalize_partial() == 0
    pinned = json.loads(pin.read_text())
    assert pinned["commit"] == "cafe01"  # capture-time, not HEAD
    assert pinned["partial_capture"] is True
    assert pinned["n_fit_windows"] == 5
    # peaks recorded at capture time survive the forced-CPU finalize
    assert pinned["peak_flops_per_chip"] == 197e12
    assert pinned["mfu_pct"] is not None
    assert pinned["backend"] == "tpu"
    assert pinned["comparison"] == "tpu-vs-cpu"
    assert not partial.exists()  # consumed


def test_finalize_prefers_richer_orphan(paths):
    partial, orphan, pin = paths
    orphan.write_text(json.dumps(_partial_payload(6, commit="older")))
    partial.write_text(json.dumps(_partial_payload(3, commit="newer")))
    assert bench.finalize_partial() == 0
    pinned = json.loads(pin.read_text())
    assert pinned["commit"] == "older" and pinned["n_fit_windows"] == 6
    assert not orphan.exists() and not partial.exists()


def test_finalize_never_downgrades_partial_pin(paths, capsys):
    partial, _, pin = paths
    rich = {"backend": "tpu", "partial_capture": True,
            "fit_windows": [1.0] * 6, "n_fit_windows": 6, "value": 1.0}
    pin.write_text(json.dumps(rich))
    partial.write_text(json.dumps(_partial_payload(4)))
    assert bench.finalize_partial() == 0
    assert json.loads(pin.read_text()) == rich  # untouched
    assert "keeping it" in capsys.readouterr().out
    assert not partial.exists()  # candidate discarded


def test_finalize_never_overwrites_full_pin(paths, capsys):
    partial, _, pin = paths
    full = {"backend": "tpu", "fit_windows": [1.0] * 2, "value": 1.0}
    pin.write_text(json.dumps(full))
    partial.write_text(json.dumps(_partial_payload(6)))
    assert bench.finalize_partial() == 0
    assert json.loads(pin.read_text()) == full
    assert "full pin already exists" in capsys.readouterr().out


def test_finalize_upgrades_partial_pin_with_more_windows(paths):
    partial, _, pin = paths
    pin.write_text(json.dumps({"backend": "tpu", "partial_capture": True,
                               "fit_windows": [1.0] * 3,
                               "n_fit_windows": 3, "value": 1.0}))
    partial.write_text(json.dumps(_partial_payload(5)))
    assert bench.finalize_partial() == 0
    assert json.loads(pin.read_text())["n_fit_windows"] == 5


def test_finalize_prefers_tpu_salvage_over_more_cpu_windows(paths):
    partial, orphan, pin = paths
    orphan.write_text(json.dumps(_partial_payload(4, commit="chip")))
    partial.write_text(json.dumps(
        _partial_payload(6, backend="cpu", commit="fallback")))
    assert bench.finalize_partial() == 0
    pinned = json.loads(pin.read_text())
    assert pinned["commit"] == "chip" and pinned["n_fit_windows"] == 4


def test_finalize_folds_journal_stitch_outranking_partial(paths, capsys):
    """--finalize-partial folds into journal replay (ISSUE 17): a
    stitchable TPU capture journal outranks a CPU partial file and
    promotes with full stitch provenance."""
    from tests.test_capture import _fake_journal

    partial, _, pin = paths
    partial.write_text(json.dumps(
        _partial_payload(6, backend="cpu", commit="fallback")))
    with open(bench._JOURNAL, "w") as f:
        for r in _fake_journal(3, backend="tpu", commit="chipchip"):
            f.write(json.dumps(r) + "\n")
    assert bench.finalize_partial() == 0
    result = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert result["stitched"] is True
    assert result["backend"] == "tpu"
    assert len(result["windows_provenance"]) == 9
    pinned = json.loads(pin.read_text())
    assert pinned["commit"] == "chipchip"  # stitch identity, not HEAD
    assert not partial.exists()  # consumed


def test_finalize_skips_unstitchable_journal_loudly(paths, capsys):
    """A journal whose fragments refuse to stitch never blocks the
    file-based salvage path — the refusal is printed, not silent."""
    from tests.test_capture import _fake_journal

    partial, _, pin = paths
    partial.write_text(json.dumps(_partial_payload(5)))
    recs = (_fake_journal(2, commit="aaa") + _fake_journal(2, commit="bbb"))
    with open(bench._JOURNAL, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    assert bench.finalize_partial() == 0
    out = capsys.readouterr().out
    assert "not stitchable" in out
    assert json.loads(pin.read_text())["n_fit_windows"] == 5


def test_discard_keeps_promotable_tpu_salvage_on_cpu_fallback(paths):
    partial, orphan, _ = paths
    orphan.write_text(json.dumps(_partial_payload(5)))
    partial.write_text(json.dumps(_partial_payload(6, backend="cpu")))
    bench._discard_partials(keep_tpu_salvage=True)
    assert orphan.exists(), "TPU salvage must survive a CPU fallback"
    assert not partial.exists(), "the fallback's own partial is superseded"
    bench._discard_partials()
    assert not orphan.exists(), "unconditional discard clears everything"


def test_salvage_rank_orders_backend_then_windows():
    tpu3 = _partial_payload(3)
    tpu5 = _partial_payload(5)
    cpu9 = _partial_payload(9, backend="cpu")
    assert bench._salvage_rank(tpu3) > bench._salvage_rank(cpu9)
    assert bench._salvage_rank(tpu5) > bench._salvage_rank(tpu3)
    assert bench._salvage_rank(None) < bench._salvage_rank(cpu9)


def test_assemble_result_degrades_missing_phases_to_none():
    r = bench._assemble_result(
        fit_w=[100.0, 110.0, 105.0], ceil_w=[], cceil_w=[], unstaged_w=[],
        flops_per_graph=None, bytes_per_graph=None, baseline=50.0,
        backend="tpu", fallback=False, train_graphs=478,
        partial_capture=True)
    assert r["value"] == 105.0 and r["vs_baseline"] == 2.1
    for k in ("ceiling_graphs_per_s", "fit_over_ceiling", "mfu_pct",
              "staged_over_unstaged", "compact_over_packed",
              "roofline_graphs_per_s"):
        assert r[k] is None, k
    assert r["partial_capture"] is True and r["n_fit_windows"] == 3


def test_assemble_result_uses_peak_overrides():
    r = bench._assemble_result(
        fit_w=[100.0], ceil_w=[200.0], cceil_w=[150.0], unstaged_w=[80.0],
        flops_per_graph=1e9, bytes_per_graph=1e6, baseline=50.0,
        backend="tpu", fallback=False, train_graphs=1,
        peak_flops=1e12, peak_bw=1e11)
    assert r["mfu_pct"] == pytest.approx(10.0)  # 100*1e9/1e12
    assert r["mbu_pct"] == pytest.approx(0.1)
    assert r["roofline_graphs_per_s"] == pytest.approx(1000.0)
    assert r["fit_over_ceiling"] == 0.5
    assert r["staged_over_unstaged"] == 1.25


# --- bench.py --gate: throughput-regression gate vs BENCH_r* history ----


def _hist(round_name, value, spread, backend="cpu", impl="segment"):
    return {"_round": round_name, "value": value,
            "fit_spread_pct": spread, "backend": backend,
            "attention_impl": impl}


class TestGate:
    def test_pass_within_spread(self):
        ok, d = bench.gate_check(
            {"value": 950.0, "backend": "cpu"},
            [_hist("BENCH_r01.json", 1000.0, 10.0)])
        assert ok and d["verdict"] == "pass"
        assert d["floor_graphs_per_s"] == 900.0

    def test_fail_beyond_spread(self):
        ok, d = bench.gate_check(
            {"value": 850.0, "backend": "cpu"},
            [_hist("BENCH_r01.json", 1000.0, 10.0)])
        assert not ok and "FAIL" in d["verdict"]

    def test_reference_is_most_recent_comparable(self):
        """r03 measured 4299 on a fast host, r05 measured 2868 on a slow
        one — the gate must reference the LATEST round, not the
        historical max, or host variance reads as a code regression."""
        hist = [_hist("BENCH_r03.json", 4299.3, 4.7),
                _hist("BENCH_r05.json", 2868.4, 17.1)]
        ok, d = bench.gate_check({"value": 2800.0, "backend": "cpu"},
                                 hist)
        assert ok and d["reference_round"] == "BENCH_r05.json"

    def test_backend_and_variant_scope_comparability(self):
        hist = [_hist("BENCH_r01.json", 9000.0, 1.0, backend="tpu"),
                _hist("BENCH_r02.json", 1000.0, 1.0,
                      impl="blocked_dense")]
        ok, d = bench.gate_check({"value": 5.0, "backend": "cpu"}, hist)
        assert ok and "no comparable history" in d["verdict"]
        ok2, d2 = bench.gate_check(
            {"value": 995.0, "backend": "cpu",
             "attention_impl": "blocked_dense"}, hist)
        assert ok2 and d2["reference_round"] == "BENCH_r02.json"

    def test_latency_metric_gates_upward(self):
        """A latency headline regresses by RISING: the gate must fail a
        doubling and pass an improvement, not the other way around."""
        hist = [{"_round": "BENCH_r09.json", "value": 5.0,
                 "fit_spread_pct": 20.0, "backend": "cpu",
                 "metric": "pert_serve_request_latency_ms_p50",
                 "unit": "ms"}]
        run = {"backend": "cpu", "unit": "ms",
               "metric": "pert_serve_request_latency_ms_p50"}
        ok, d = bench.gate_check({**run, "value": 10.0}, hist)
        assert not ok and "ceiling" in d["verdict"]
        ok, d = bench.gate_check({**run, "value": 4.0}, hist)
        assert ok and d["ceiling_ms"] == 6.0

    def test_fallback_capture_is_refused_as_variant_witness(self):
        """kernel_fallbacks > 0 means the programs (partly) traced the
        segment path — the gate must refuse the capture outright rather
        than compare segment numbers against the claimed variant."""
        ok, d = bench.gate_check(
            {"value": 9999.0, "backend": "cpu",
             "attention_impl": "pallas_fused", "kernel_fallbacks": 2},
            [])
        assert not ok and "fallback" in d["verdict"]
        # a segment run with the (vacuous) zero stamp still gates
        ok, _ = bench.gate_check(
            {"value": 100.0, "backend": "cpu", "kernel_fallbacks": 0},
            [_hist("BENCH_r01.json", 100.0, 5.0)])
        assert ok

    def test_history_loader_skips_failed_rounds(self, tmp_path):
        good = {"n": 1, "rc": 0,
                "parsed": {"value": 100.0, "backend": "cpu",
                           "fit_spread_pct": 5.0}}
        bad_rc = {"n": 2, "rc": 1, "parsed": {"value": 1.0}}
        no_parse = {"n": 3, "rc": 0, "tail": "exploded"}
        for name, payload in (("BENCH_r01.json", good),
                              ("BENCH_r02.json", bad_rc),
                              ("BENCH_r03.json", no_parse)):
            (tmp_path / name).write_text(json.dumps(payload))
        recs = bench._history_records(str(tmp_path))
        assert [r["_round"] for r in recs] == ["BENCH_r01.json"]

    def test_gate_main_round_trip(self, tmp_path, capsys):
        res = tmp_path / "result.json"
        res.write_text(json.dumps({"value": 2800.0, "backend": "cpu",
                                   "attention_impl": "segment"}))
        rc = bench.gate_main([str(res)])
        out = json.loads(capsys.readouterr().out)
        assert "gate" in out and isinstance(rc, int)
        # against the REAL repo history: a value inside r05's spread
        # window passes (the acceptance criterion's CPU check)
        assert rc == 0
