"""Crash-resume drill as a suite regression (VERDICT r4 #5).

The full-scale drill lives in benchmarks/endurance_drill.py; this runs
the same parent orchestration — control run, kill -9 once an epoch is
logged, resume from the last committed orbax checkpoint — at smoke
scale, so the recovery contract (resume epoch = last committed + 1,
final metrics equal to the uninterrupted control) is pinned on every
suite run, not just when the benchmark is invoked.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_crash_resume_drill_smoke():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # single-device child is fastest
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "endurance_drill.py"),
         "--epochs", "4", "--kill-after-epoch", "1", "--timeout", "400"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=500)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    row = json.loads(r.stdout.strip().splitlines()[-1])
    assert row["value"] is True
    assert row["resume_contract_ok"] and row["parity_ok"]
    assert row["resume_started_at_epoch"] == \
        row["latest_committed_checkpoint"] + 1
    assert row["rel_diff"] <= row["rtol"]
