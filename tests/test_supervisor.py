"""Crash/hang supervisor (train/supervisor.py): the runtime answer to the
failure modes the reference cannot survive (no model checkpointing —
SURVEY.md §5.3/5.4) and this environment demonstrated (a device transport
that wedges inside a blocked call, raising nothing).

The generic tests drive `supervise` with scripted children (crash once,
hang forever, always-fail) against real subprocesses; the CLI tests pin
the train_main wiring (flag stripping, checkpoint_dir requirement, child
re-entry guard). Resume CORRECTNESS is pinned elsewhere at full scale
(tests/test_endurance.py smoke; benchmarks/endurance_r5.jsonl bit-exact).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from pertgnn_tpu.cli.train_main import _strip_flags
from pertgnn_tpu.train import supervisor


def _script(tmp_path, body: str) -> list[str]:
    path = tmp_path / "child.py"
    path.write_text(textwrap.dedent(body))
    return [sys.executable, str(path)]


def test_crash_then_succeed_restarts_and_returns_zero(tmp_path):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    cmd = _script(tmp_path, f"""
        import os, sys
        marker = {str(tmp_path / 'ran_once')!r}
        os.makedirs(os.path.join({str(ckpt)!r}, "0"), exist_ok=True)
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(3)          # first attempt: crash after 'epoch 0'
        sys.exit(0)              # second attempt: resume and finish
    """)
    rc = supervisor.supervise(cmd, str(ckpt), max_restarts=2,
                              hang_timeout=60.0, poll_interval=0.2)
    assert rc == 0
    assert (tmp_path / "ran_once").exists()


def test_hang_is_killed_and_restarted(tmp_path):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    pidfile = tmp_path / "hung_pid"
    cmd = _script(tmp_path, f"""
        import os, sys, time
        marker = {str(tmp_path / 'ran_once')!r}
        if not os.path.exists(marker):
            open(marker, "w").close()
            open({str(pidfile)!r}, "w").write(str(os.getpid()))
            time.sleep(600)      # wedge: alive but no progress, forever
        sys.exit(0)
    """)
    # hang_timeout must also cover the RESTARTED child's interpreter
    # startup on a loaded single-core host — 2 s flaked there
    rc = supervisor.supervise(cmd, str(ckpt), max_restarts=1,
                              hang_timeout=10.0, poll_interval=0.3)
    assert rc == 0
    # the hung first attempt must actually be dead, not orphaned
    hung_pid = int(pidfile.read_text())
    with pytest.raises(OSError):
        os.kill(hung_pid, 0)


def test_restart_budget_exhausted_returns_last_code(tmp_path):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    cmd = _script(tmp_path, "import sys; sys.exit(5)")
    rc = supervisor.supervise(cmd, str(ckpt), max_restarts=1,
                              hang_timeout=60.0, poll_interval=0.2)
    assert rc == 5


def test_child_gets_reentry_marker(tmp_path):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    out = tmp_path / "marker_value"
    cmd = _script(tmp_path, f"""
        import os
        open({str(out)!r}, "w").write(
            os.environ.get({supervisor.CHILD_ENV_MARKER!r}, "absent"))
    """)
    assert supervisor.supervise(cmd, str(ckpt), max_restarts=0,
                                hang_timeout=60.0, poll_interval=0.2) == 0
    assert out.read_text() == "1"


def test_supervisor_death_takes_the_child_with_it(tmp_path):
    """SIGTERM to the supervisor (job-manager preemption) must not orphan
    the detached training child — it lives in its own session, so only
    the supervisor's cleanup can reach it."""
    import signal
    import time as _time

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    cpid_file = tmp_path / "cpid"
    child_body = (f"import os,time; open({str(cpid_file)!r},'w')"
                  f".write(str(os.getpid())); time.sleep(600)")
    sup_body = (
        "import sys\n"
        "from pertgnn_tpu.train import supervisor\n"
        f"supervisor.supervise([sys.executable, '-c', {child_body!r}],\n"
        f"    {str(ckpt)!r}, max_restarts=0, hang_timeout=600.0,\n"
        "    poll_interval=0.2)\n")
    sup = subprocess.Popen([sys.executable, "-c", sup_body])
    deadline = _time.monotonic() + 60
    while not cpid_file.exists() and _time.monotonic() < deadline:
        _time.sleep(0.2)
    assert cpid_file.exists(), "child never started"
    child_pid = int(cpid_file.read_text())
    sup.send_signal(signal.SIGTERM)
    assert sup.wait(timeout=30) != 0
    deadline = _time.monotonic() + 10
    while _time.monotonic() < deadline:
        try:
            os.kill(child_pid, 0)
        except OSError:
            break  # child is gone — cleanup worked
        _time.sleep(0.2)
    else:
        os.kill(child_pid, 9)  # don't leak it even when failing the test
        pytest.fail("child survived its supervisor")


def test_restart_backoff_schedule():
    """Pure schedule: exponential from base, clamped at cap, zero when
    disabled — pinned without sleeping through it."""
    assert supervisor.restart_backoff(0, 1.0, 60.0) == 0.0
    assert supervisor.restart_backoff(1, 1.0, 60.0) == 1.0
    assert supervisor.restart_backoff(2, 1.0, 60.0) == 2.0
    assert supervisor.restart_backoff(3, 1.0, 60.0) == 4.0
    assert supervisor.restart_backoff(9, 1.0, 60.0) == 60.0  # capped
    assert supervisor.restart_backoff(5, 0.0, 60.0) == 0.0  # disabled


def test_crash_loop_backs_off_and_counts(tmp_path):
    """A child that dies instantly is the crash-loop signature: each
    restart must wait the (escalating) backoff instead of respawning
    immediately, and supervisor.crash_loop must count every fast death
    distinctly from plain supervisor.crash."""
    import time as _time

    from pertgnn_tpu import telemetry
    from pertgnn_tpu.telemetry import (MetricsWriter, TelemetryBus,
                                       load_events)

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    cmd = _script(tmp_path, "import sys; sys.exit(7)")
    writer = MetricsWriter(str(tmp_path / "tele"))
    bus = TelemetryBus(writer, level="trace")
    prev = telemetry.set_bus(bus)
    t0 = _time.monotonic()
    try:
        rc = supervisor.supervise(cmd, str(ckpt), max_restarts=2,
                                  hang_timeout=60.0, poll_interval=0.1,
                                  backoff_base=0.2, backoff_cap=0.3,
                                  min_uptime_s=30.0)
    finally:
        telemetry.set_bus(prev)
        bus.close()
    elapsed = _time.monotonic() - t0
    assert rc == 7
    events = load_events(writer.path)
    crash_loops = [e for e in events if e["name"] == "supervisor.crash_loop"]
    assert len(crash_loops) == 3  # every attempt died within min_uptime
    backoffs = [e["value"] for e in events
                if e["name"] == "supervisor.backoff_s"]
    assert backoffs == [0.2, 0.3]  # 0.2 * 2 = 0.4 clamped to the cap
    assert elapsed >= 0.5  # the sleeps actually happened


def test_long_uptime_is_not_a_crash_loop(tmp_path):
    """A child that outlives min_uptime_s before dying must not count as
    a crash loop (and the backoff stays at base)."""
    from pertgnn_tpu import telemetry
    from pertgnn_tpu.telemetry import (MetricsWriter, TelemetryBus,
                                       load_events)

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    cmd = _script(tmp_path, f"""
        import os, sys, time
        marker = {str(tmp_path / 'ran_once')!r}
        time.sleep(0.5)
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(3)
        sys.exit(0)
    """)
    writer = MetricsWriter(str(tmp_path / "tele"))
    bus = TelemetryBus(writer, level="trace")
    prev = telemetry.set_bus(bus)
    try:
        rc = supervisor.supervise(cmd, str(ckpt), max_restarts=2,
                                  hang_timeout=60.0, poll_interval=0.1,
                                  backoff_base=0.1, backoff_cap=1.0,
                                  min_uptime_s=0.3)
    finally:
        telemetry.set_bus(prev)
        bus.close()
    assert rc == 0
    events = load_events(writer.path)
    assert not [e for e in events if e["name"] == "supervisor.crash_loop"]
    assert [e["value"] for e in events
            if e["name"] == "supervisor.backoff_s"] == [0.1]


def test_progress_token_tracks_entries_and_mtime(tmp_path):
    assert supervisor.progress_token(str(tmp_path / "nope")) == ("missing",)
    t0 = supervisor.progress_token(str(tmp_path))
    (tmp_path / "0").mkdir()
    t1 = supervisor.progress_token(str(tmp_path))
    assert t1 != t0
    # deep write churn (a file inside the step dir) must also register —
    # that's what keeps a long single checkpoint write looking alive
    (tmp_path / "0" / "shard").write_text("x")
    future = __import__("time").time() + 10
    os.utime(tmp_path / "0" / "shard", (future, future))
    assert supervisor.progress_token(str(tmp_path)) != t1


def test_strip_flags_both_forms():
    argv = ["--synthetic", "--supervise", "3", "--epochs", "2",
            "--hang_timeout=5", "--checkpoint_dir", "d"]
    assert _strip_flags(argv, ("--supervise", "--hang_timeout")) == [
        "--synthetic", "--epochs", "2", "--checkpoint_dir", "d"]


def test_cli_supervise_requires_checkpoint_dir(capsys):
    from pertgnn_tpu.cli import train_main

    with pytest.raises(SystemExit) as e:
        train_main.main(["--synthetic", "--supervise", "1"])
    assert e.value.code == 2  # argparse error
    assert "--checkpoint_dir" in capsys.readouterr().err


def test_cli_supervised_run_resumes_from_checkpoint(tmp_path):
    """End-to-end through the real CLI: a prior interrupted run left a
    committed checkpoint (simulated by training 2 of 4 epochs to
    completion — deterministic, unlike racing a SIGKILL against
    sub-second epochs); the supervised run must resume from it and
    finish the remaining epochs with exit 0. Kill/hang semantics are
    pinned by the scripted-children tests above and (bit-exactly, at
    scale) by the endurance drill."""
    ckpt = tmp_path / "ckpt"

    def argv(epochs):
        # explicit --artifact_dir keeps the child hermetic (the default
        # ./processed would read/poison a real cache in the repo cwd)
        return ["-m", "pertgnn_tpu.cli.train_main", "--synthetic",
                "--synthetic_entries", "2", "--synthetic_traces_per_entry",
                "60", "--min_traces_per_entry", "5", "--epochs",
                str(epochs), "--label_scale", "1000",
                "--artifact_dir", str(tmp_path / "art"),
                "--checkpoint_dir", str(ckpt)]

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    p = subprocess.run([sys.executable, *argv(2)], env=env,
                       stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL, timeout=600)
    assert p.returncode == 0
    committed = {int(c.name) for c in ckpt.iterdir() if c.name.isdigit()}
    assert 1 in committed  # epochs 0..1 done

    rc = supervisor.supervise(
        [sys.executable, *argv(4)], str(ckpt), max_restarts=1,
        hang_timeout=600.0, poll_interval=1.0)
    assert rc == 0
    steps = {int(c.name) for c in ckpt.iterdir() if c.name.isdigit()}
    assert max(steps) == 3  # resumed and committed epochs 2..3
