"""Adversarial real-data-shaped ingest fuzzing (VERDICT r4 #2).

Every byte the loaders had seen before this module was well-formed
synthetic output. The real 200 GB Alibaba MSCallGraph/MSResource trees
(/root/reference/README.md:4-12) carry documented dirt: the `(?)` um
token (reference preprocess.py:121), negative rt (preprocess.py:114),
NaN cells, messy dtypes, duplicated and truncated shard files. For each
anomaly this module either pins OUR behavior to the reference's
(preprocess.py:99-149 entry detection; :203-213 load/dedupe/sort) or
exercises the documented PARITY divergence and its guard.

Harness: corrupt a small synthetic corpus ONE way at a time, run the
real loaders (`load_raw_csvs`, `load_raw_csvs_streaming`) + `preprocess`
+ `build_dataset` over it, and assert the documented outcome — no path
may silently return wrong answers.
"""

import os
import shutil

import numpy as np
import pandas as pd
import pytest

from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import Config, DataConfig, IngestConfig
from pertgnn_tpu.ingest import synthetic
from pertgnn_tpu.ingest.io import (
    load_raw_csvs,
    load_raw_csvs_streaming,
)
from pertgnn_tpu.ingest.preprocess import detect_entries, preprocess

CFG = IngestConfig(min_traces_per_entry=5)


def _corpus(tmp_path, shards=3, seed=3):
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_entries=4, traces_per_entry=40, seed=seed))
    root = str(tmp_path / "raw")
    synthetic.write_csvs(data, root, shards=shards)
    return root


def _pipeline_counts(root, cfg=CFG):
    spans, resources = load_raw_csvs(root)
    pre = preprocess(spans, resources, cfg)
    return (pre.stats["num_traces_final"], pre.stats["num_entries_final"],
            len(pre.spans), pre)


# ---------------------------------------------------------------------------
# shard-file corruption
# ---------------------------------------------------------------------------

def test_missing_column_raises_with_shard_path(tmp_path):
    root = _corpus(tmp_path)
    shard = os.path.join(root, "MSCallGraph", "MSCallGraph_1.csv")
    df = pd.read_csv(shard)
    df.drop(columns=["rt"]).to_csv(shard, index=False)
    with pytest.raises(ValueError, match="MSCallGraph_1.csv.*rt"):
        load_raw_csvs(root)
    with pytest.raises(ValueError, match="MSCallGraph_1.csv.*rt"):
        load_raw_csvs_streaming(root, CFG)


def test_extra_columns_are_dropped(tmp_path):
    root = _corpus(tmp_path)
    clean = _pipeline_counts(root)[:3]
    shard = os.path.join(root, "MSCallGraph", "MSCallGraph_0.csv")
    df = pd.read_csv(shard)
    df["nodeid"] = "extra"
    df["uminstanceid"] = np.arange(len(df))
    df.to_csv(shard, index=False)
    assert _pipeline_counts(root)[:3] == clean


def test_duplicate_shard_is_a_noop(tmp_path):
    # a shard copied twice into the tree (interrupted rsync): global
    # row dedupe (reference preprocess.py:212) must absorb it on BOTH
    # loader paths
    root = _corpus(tmp_path)
    clean = _pipeline_counts(root)[:3]
    cg = os.path.join(root, "MSCallGraph")
    shutil.copy(os.path.join(cg, "MSCallGraph_0.csv"),
                os.path.join(cg, "MSCallGraph_0_copy.csv"))
    assert _pipeline_counts(root)[:3] == clean

    spans, resources, tcfg, _ = load_raw_csvs_streaming(root, CFG)
    pre = preprocess(spans, resources, tcfg)
    assert (pre.stats["num_traces_final"], pre.stats["num_entries_final"],
            len(pre.spans)) == clean


def test_truncated_shard_parses_or_raises_cleanly(tmp_path):
    # a shard cut mid-row (partial copy). CSV parsers either recover the
    # complete prefix rows or fail; what is FORBIDDEN is a bare parser
    # traceback without the shard path, or a silent wrong answer beyond
    # the lost suffix rows.
    root = _corpus(tmp_path)
    shard = os.path.join(root, "MSCallGraph", "MSCallGraph_2.csv")
    raw = open(shard, "rb").read()
    open(shard, "wb").write(raw[:int(len(raw) * 0.7)])
    try:
        spans, _ = load_raw_csvs(root)
    except ValueError as e:
        assert "MSCallGraph_2.csv" in str(e)
    else:
        full = pd.read_csv(
            os.path.join(root, "MSCallGraph", "MSCallGraph_0.csv"))
        # recovered rows must still be schema-complete
        assert not spans["traceid"].isna().any()
        assert len(spans) < len(full) * 3


def test_empty_shard_file(tmp_path):
    root = _corpus(tmp_path)
    shard = os.path.join(root, "MSCallGraph", "MSCallGraph_9.csv")
    open(shard, "w").close()  # zero bytes
    with pytest.raises(ValueError, match="MSCallGraph_9.csv"):
        load_raw_csvs(root)


# ---------------------------------------------------------------------------
# reference-documented value dirt
# ---------------------------------------------------------------------------

def _trace_rows(traceid, rows):
    """rows: (timestamp, rpcid, um, rpctype, dm, interface, rt)"""
    return pd.DataFrame(
        [(traceid, *r) for r in rows],
        columns=["traceid", "timestamp", "rpcid", "um", "rpctype", "dm",
                 "interface", "rt"])


def test_qmark_um_breaks_entry_tie():
    # two same-timestamp same-|rt| http rows: the reference keeps the
    # um == "(?)" one (preprocess.py:121); a third trace with NO (?) row
    # among its ties is dropped as ambiguous
    df = pd.concat([
        _trace_rows("t1", [(0, "0", "(?)", "http", "A", "if0", 100.0),
                           (0, "0.1", "B", "http", "C", "if1", -100.0),
                           (1, "0.2", "A", "rpc", "D", "if2", 30.0)]),
        _trace_rows("t2", [(0, "0", "X", "http", "A", "if0", 50.0),
                           (0, "0.1", "Y", "http", "C", "if1", 50.0)]),
    ], ignore_index=True)
    out, stats = detect_entries(df)
    assert set(out["traceid"]) == {"t1"}
    assert (out["entryid"] == "A_if0").all()
    assert stats["num_ambiguous_entry"] == 1


def test_negative_rt_on_entry_row():
    # raw traces carry negative rt; the reference compares |rt|
    # (preprocess.py:114) and labels with max |rt| — a negative-rt entry
    # row must still win the candidacy and the label must be its |rt|
    df = _trace_rows("t1", [(0, "0", "(?)", "http", "A", "if0", -500.0),
                            (1, "0.1", "A", "rpc", "B", "if1", 400.0)])
    out, _ = detect_entries(df)
    assert set(out["traceid"]) == {"t1"}
    res = pd.DataFrame({"timestamp": [0], "msname": ["A"],
                        "instance_cpu_usage": [0.5],
                        "instance_memory_usage": [0.5]})
    pre = preprocess(df, res, IngestConfig(min_traces_per_entry=0,
                                           min_resource_coverage=0.0))
    assert pre.stats["num_traces_final"] == 1
    # endTimestamp uses |rt| (reference preprocess.py:263)
    assert (pre.spans["endTimestamp"]
            == pre.spans["timestamp"] + pre.spans["rt"].abs()).all()


def test_nan_rt_rows_never_become_entries():
    # numeric NaN rt: pandas max() skips NaN, NaN == max is False, so a
    # NaN-rt row can't be a candidate; a trace whose EVERY rt is NaN has
    # no candidates and is dropped (matches the reference's groupby loop)
    df = pd.concat([
        _trace_rows("t1", [(0, "0", "(?)", "http", "A", "if0", np.nan),
                           (0, "0.1", "A", "http", "B", "if1", 80.0)]),
        _trace_rows("t2", [(0, "0", "(?)", "http", "A", "if0", np.nan),
                           (1, "0.1", "A", "rpc", "B", "if1", np.nan)]),
    ], ignore_index=True)
    out, stats = detect_entries(df)
    assert set(out["traceid"]) == {"t1"}
    assert (out["entryid"] == "B_if1").all()  # the finite-rt row won
    assert stats["num_without_entry"] == 1


def test_empty_string_um_dm_flow_through():
    # "" is a legal token — distinct from "nan" and "(?)"; it must ride
    # the whole pipeline as an ordinary microservice name
    df = _trace_rows("t1", [(0, "0", "(?)", "http", "", "if0", 90.0),
                            (1, "0.1", "", "rpc", "B", "if1", 10.0)])
    res = pd.DataFrame({"timestamp": [0, 0],
                        "msname": ["", "B"],
                        "instance_cpu_usage": [0.1, 0.2],
                        "instance_memory_usage": [0.3, 0.4]})
    pre = preprocess(df, res, IngestConfig(min_traces_per_entry=0,
                                           min_resource_coverage=0.0))
    assert pre.stats["num_traces_final"] == 1
    assert "" in set(pre.ms_vocab)


def test_non_monotonic_timestamps_match_sorted_input(tmp_path):
    # raw shards arrive in arbitrary order; the reference sorts by
    # timestamp before factorizing (preprocess.py:213) so row order must
    # not leak into the output. Distinct timestamps -> the stable sort
    # fully determines order -> identical PreprocessResult.
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_entries=3, traces_per_entry=30, seed=11))
    spans = data.spans.copy()
    spans["timestamp"] = (spans["timestamp"].astype(np.int64) * 1000
                          + np.random.default_rng(0).permutation(len(spans)))
    shuffled = spans.sample(frac=1.0, random_state=7).reset_index(drop=True)
    a = preprocess(spans, data.resources, CFG)
    b = preprocess(shuffled, data.resources, CFG)
    pd.testing.assert_frame_equal(a.spans, b.spans)
    np.testing.assert_array_equal(a.ms_vocab, b.ms_vocab)
    assert a.stats == b.stats


def test_int64_range_timestamps_end_to_end():
    # timestamps near 2^52: the 30 s bucket is ~2^52 too — beyond the
    # featurize packed-key bound (2^40), forcing the MultiIndex path —
    # and the whole pipeline down to a packed batch must stay exact
    # bucket-aligned shift (a multiple of the 30 s bucket) so trace
    # buckets still land on resource timestamps after the shift
    base = (np.int64(1) << 52) // 30_000 * 30_000
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_entries=3, traces_per_entry=40, seed=5))
    spans = data.spans.copy()
    spans["timestamp"] = spans["timestamp"].astype(np.int64) + base
    res = data.resources.copy()
    res["timestamp"] = res["timestamp"].astype(np.int64) + base
    cfg = Config(ingest=CFG, data=DataConfig(batch_size=16))
    pre = preprocess(spans, res, cfg.ingest)
    assert pre.stats["num_traces_final"] > 0
    ds = build_dataset(pre, cfg)
    batch = next(ds.batches("train"))
    x = np.asarray(batch.x)
    assert np.isfinite(x).all()
    # featurization found real table rows (not all-missing): some node
    # has the missing indicator at 0
    assert (x[np.asarray(batch.node_mask), -1] == 0).any()


def test_all_filtered_corpus_raises_cleanly(tmp_path):
    # no http rows at all -> every trace is dropped at entry detection;
    # build_dataset must refuse with the diagnostic, not crash deeper
    df = _trace_rows("t1", [(0, "0", "A", "rpc", "B", "if0", 10.0)])
    res = pd.DataFrame({"timestamp": [0], "msname": ["A"],
                        "instance_cpu_usage": [0.1],
                        "instance_memory_usage": [0.1]})
    pre = preprocess(df, res, IngestConfig())
    assert pre.stats["num_traces_final"] == 0
    with pytest.raises(ValueError, match="no traces survived"):
        build_dataset(pre, Config(ingest=IngestConfig()))


def test_streaming_handles_all_nan_um_shard(tmp_path):
    # an all-NaN um column in one shard: the stream vocab normalizes to
    # the literal "nan" exactly like the exact path's fillna — final
    # trace counts must agree between the two loaders
    root = _corpus(tmp_path, shards=2)
    shard = os.path.join(root, "MSCallGraph", "MSCallGraph_1.csv")
    df = pd.read_csv(shard)
    df["um"] = np.nan
    df.to_csv(shard, index=False)
    exact_counts = _pipeline_counts(root)[:2]
    spans, resources, tcfg, vocabs = load_raw_csvs_streaming(root, CFG)
    pre = preprocess(spans, resources, tcfg)
    assert (pre.stats["num_traces_final"],
            pre.stats["num_entries_final"]) == exact_counts
    assert vocabs["ms"].code_of("nan") >= 0
