"""Real-writer child for the graftvault crash matrix
(tests/test_durable.py). Not named test_* on purpose: launched as a
subprocess, never collected.

Protocol (argv: MODE ROOT OUT_DIR):

1. disarm fault injection (``faults.install(None)`` — an explicit
   install also blocks later silent env adoption),
2. write the OLD entry and dump ``<OUT_DIR>/old.json`` (the
   normalized relpath -> sha256 snapshot of ROOT),
3. re-arm EXPLICITLY from ``$PERTGNN_FAULT_PLAN``
   (``faults.install(FaultPlan.from_env())`` — step 1 set
   ``_ENV_CHECKED``, so adoption must be explicit),
4. write the NEW entry — in a kill run, durable.py's ``_fire`` enacts
   ``os._exit(137)`` at the armed ``store.write.*`` site, the closest
   a test can get to power loss,
5. (unarmed reference runs only) dump ``<OUT_DIR>/new.json``, exit 0.

The parent asserts: exit 137, and the reopened ROOT's snapshot equals
the reference run's OLD or NEW snapshot exactly — never a third thing.

Determinism: ``time.time`` / ``time.monotonic`` / ``os.getpid`` are
frozen to constants before any store import, so the bytes the
reference run and every kill run write are identical (manifests embed
creation times; journal records embed pid + clocks). The race mode
(two live writers) skips the pid freeze — pid-suffixed tmp names are
part of what it exercises.

Modes: ``aot`` | ``arena`` | ``delta`` | ``sidecar`` | ``journal``
(the five stores), ``race-aot`` (concurrent-writer drill: spin on
``<OUT_DIR>/go`` then warm-save the shared entry once).
"""

import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FROZEN_TIME = 1_700_000_000.0
FROZEN_PID = 4242


def snapshot(root: str) -> dict:
    """Normalized relpath -> sha256 of a store root: crash residue —
    pid-stamped tmp files/dirs, the advisory lock, the quarantine dir,
    and GENERATIONS NO MANIFEST REFERENCES — is excluded, because a
    killed writer legitimately leaves it behind (graftvault scrub
    sweeps it) and it is invisible to every reader."""
    referenced: set[str] = set()
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            if fn.endswith(".manifest.json") or (
                    fn.endswith(".json") and "@g" not in fn):
                path = os.path.join(dirpath, fn)
                try:
                    with open(path, "rb") as f:
                        env = json.loads(f.read().decode("utf-8"))
                    body = env.get("body", env)
                    for field in ("dir", "blob"):
                        name = body.get(field)
                        if isinstance(name, str) and "@g" in name:
                            referenced.add(os.path.join(
                                os.path.relpath(dirpath, root), name))
                except (OSError, ValueError, AttributeError):
                    continue

    def excluded(rel: str) -> bool:
        parts = rel.split(os.sep)
        for i, part in enumerate(parts):
            # durable_write tmps are SUFFIXED (foo.json.tmp.<pid>),
            # EntryWriter tmp dirs are prefixed (.tmp.<key>.<pid>)
            if ".tmp." in part or part == ".quarantine" \
                    or part == ".lock":
                return True
            if "@g" in part:
                gen_rel = os.path.normpath(os.path.join(*parts[:i + 1]))
                if gen_rel not in referenced:
                    return True
        return False

    out: dict = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            rel = os.path.normpath(os.path.relpath(
                os.path.join(dirpath, fn), root))
            if excluded(rel):
                continue
            with open(os.path.join(dirpath, fn), "rb") as f:
                out[rel] = hashlib.sha256(f.read()).hexdigest()
    return out


def _dump(out_dir: str, name: str, snap: dict) -> None:
    with open(os.path.join(out_dir, name), "w") as f:
        json.dump(snap, f, indent=1, sort_keys=True)


def _freeze_clocks(*, pid: bool = True) -> None:
    import time

    time.time = lambda: FROZEN_TIME
    time.monotonic = lambda: 123.0
    if pid:
        os.getpid = lambda: FROZEN_PID


# -- one writer per store -----------------------------------------------

def _write_aot(root: str, payload: bytes) -> None:
    from pertgnn_tpu.aot.store import ExecutableStore

    store = ExecutableStore(root)
    store._save("prog", "cafe01", {"config": {"x": 1}},
                {"format": "stablehlo", "payload": payload})


def _write_entry(root: str, store_name: str, tag: bytes) -> None:
    """The arena/delta save substrate: arrays + text lines through an
    EntryWriter under the store lock, one manifest commit."""
    import numpy as np

    from pertgnn_tpu.store import durable
    from pertgnn_tpu.store.durable import StoreLock

    with StoreLock(os.path.join(root, ".lock"), store=store_name), \
            durable.EntryWriter(root, "cafe01", store=store_name) as w:
        w.put_array("arena_a.npy", np.frombuffer(tag * 64, np.uint8))
        w.put_array("arena_b.npy", np.arange(17, dtype=np.int64))
        w.put_text_lines("strings.txt", ["alpha", tag.decode("ascii")])
        w.commit({"key": "cafe01", "store_version": 2,
                  "tag": tag.decode("ascii")})


def _write_sidecar(root: str, value: int) -> None:
    # durable.write_json IS CheckpointManager.save_config minus the
    # jax.process_index()-0 guard (no jax in this child)
    from pertgnn_tpu.store import durable

    durable.write_json(os.path.join(root, "train_config.json"),
                       {"model": {"hidden_channels": value},
                        "label_scale": 1000.0},
                       store="checkpoint")


def _write_journal(root: str, step: int) -> None:
    from pertgnn_tpu.telemetry.capture import CaptureJournal

    CaptureJournal(os.path.join(root, "journal.jsonl")).stage(
        "probe", "done", step=step)


def main() -> int:
    mode, root, out_dir = sys.argv[1], sys.argv[2], sys.argv[3]
    os.makedirs(root, exist_ok=True)
    os.makedirs(out_dir, exist_ok=True)

    if mode == "race-aot":
        # concurrent-writer drill: real pids, real lock contention
        import time as _time

        from pertgnn_tpu.testing import faults

        faults.install(None)
        go = os.path.join(out_dir, "go")
        deadline = _time.perf_counter() + 10.0
        while not os.path.exists(go):
            if _time.perf_counter() > deadline:
                return 3
            _time.sleep(0.001)
        _write_aot(root, b"R" * 2048)
        return 0

    _freeze_clocks()
    from pertgnn_tpu.testing import faults

    writers = {
        "aot": lambda tag: _write_aot(root, tag * 2048),
        "arena": lambda tag: _write_entry(root, "arena", tag),
        "delta": lambda tag: _write_entry(root, "delta", tag),
        "sidecar": lambda tag: _write_sidecar(root, ord(tag)),
        "journal": lambda tag: _write_journal(root, ord(tag)),
    }
    write = writers[mode]

    faults.install(None)          # OLD write runs unarmed
    write(b"A")
    _dump(out_dir, "old.json", snapshot(root))

    faults.install(faults.FaultPlan.from_env())  # explicit re-arm
    write(b"B")                   # a kill run os._exit(137)s in here

    _dump(out_dir, "new.json", snapshot(root))
    return 0


if __name__ == "__main__":
    sys.exit(main())
