"""profile_epochs start/stop/close state machine, with a stubbed
jax.profiler (no real capture): ISSUE 2 satellite — the hook's state
machine was previously untested, notably the training-ends-mid-capture
path that must still flush the trace."""

import pytest

from pertgnn_tpu.utils.profiling import profile_epochs


class StubProfiler:
    def __init__(self):
        self.calls: list[tuple] = []

    def start_trace(self, log_dir):
        # a real double-start raises in jax.profiler; mirror that so the
        # state machine can't silently double-start
        assert not self.active, "start_trace while a trace is active"
        self.calls.append(("start", log_dir))

    def stop_trace(self):
        assert self.active, "stop_trace without an active trace"
        self.calls.append(("stop",))

    @property
    def active(self) -> bool:
        starts = sum(1 for c in self.calls if c[0] == "start")
        stops = len(self.calls) - starts
        return starts > stops


class RecordingBus:
    """Minimal bus stand-in capturing event() calls."""

    enabled = True

    def event(self, name, fields=None, **tags):
        self.events.append((name, fields, tags))

    def __init__(self):
        self.events = []


@pytest.fixture()
def stub():
    return StubProfiler()


@pytest.fixture()
def bus():
    return RecordingBus()


def test_traces_epoch_after_trigger(stub, bus):
    hook = profile_epochs("logs", epochs=(1,), profiler=stub, bus=bus)
    hook(0, {})                      # not a trigger epoch: nothing
    assert stub.calls == []
    hook(1, {})                      # trigger: capture starts for epoch 2
    assert stub.calls == [("start", "logs")]
    hook(2, {})                      # next epoch completes: trace stops
    assert stub.calls == [("start", "logs"), ("stop",)]
    hook.close()                     # nothing open: close is a no-op
    assert stub.calls == [("start", "logs"), ("stop",)]
    names = [n for n, _f, _t in bus.events]
    assert names == ["profiler.trace_start", "profiler.trace_stop"]
    stop_tags = bus.events[1][2]
    assert stop_tags == {"first_epoch": 2, "last_epoch": 2}


def test_training_ends_mid_capture_flushes(stub, bus):
    """The last configured epoch starts a capture that no later epoch
    will stop — fit() calls hook.close(), which must flush it."""
    hook = profile_epochs("logs", epochs=(0,), profiler=stub, bus=bus)
    hook(0, {})
    assert stub.active
    hook.close()
    assert not stub.active
    assert stub.calls == [("start", "logs"), ("stop",)]
    (_, _, start_tags), (stop_name, stop_fields, stop_tags) = bus.events
    assert stop_name == "profiler.trace_stop"
    assert stop_fields["final"] is True
    assert start_tags["first_epoch"] == 1
    # no epoch completed inside the capture: the cross-reference must
    # not name a phantom epoch
    assert stop_tags["last_epoch"] is None


def test_close_idempotent(stub, bus):
    hook = profile_epochs("logs", epochs=(0,), profiler=stub, bus=bus)
    hook(0, {})
    hook.close()
    hook.close()
    assert stub.calls.count(("stop",)) == 1


def test_back_to_back_capture_epochs(stub, bus):
    """Consecutive trigger epochs: each completion stops the open trace
    before starting the next — never two concurrent captures."""
    hook = profile_epochs("logs", epochs=(0, 1), profiler=stub, bus=bus)
    hook(0, {})
    hook(1, {})                      # stop epoch-1 trace, start epoch-2
    hook(2, {})
    hook.close()
    assert stub.calls == [("start", "logs"), ("stop",),
                          ("start", "logs"), ("stop",)]


def test_default_bus_is_process_global(stub):
    """Without an injected bus the hook publishes to the process-wide
    bus (a no-op by default) — it must not crash on it."""
    hook = profile_epochs("logs", epochs=(0,), profiler=stub)
    hook(0, {})
    hook.close()
    assert stub.calls == [("start", "logs"), ("stop",)]
