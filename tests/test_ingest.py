"""Ingest-layer tests: entry detection, filters, factorization, assembly.

Oracle: hand-built micro-frames pinning the reference's order-sensitive
pandas behaviors (/root/reference/preprocess.py:99-188), plus ground-truth
pattern labels from the synthetic generator.
"""

import numpy as np
import pandas as pd
import pytest

from pertgnn_tpu.config import IngestConfig
from pertgnn_tpu.ingest.assemble import assemble
from pertgnn_tpu.ingest.preprocess import (
    build_resource_table,
    detect_entries,
    factorize_columns,
    filter_by_entry_occurrence,
    filter_by_resource_coverage,
    preprocess,
)


def _spans(rows):
    return pd.DataFrame(
        rows,
        columns=["traceid", "timestamp", "rpcid", "um", "rpctype", "dm",
                 "interface", "rt"],
    )


class TestDetectEntries:
    def test_single_candidate(self):
        df = _spans([
            ("t1", 0, "0", "(?)", "http", "A", "if1", 100),
            ("t1", 1, "0.1", "A", "rpc", "B", "if2", 50),
        ])
        out, stats = detect_entries(df)
        assert set(out["traceid"]) == {"t1"}
        assert (out["entryid"] == "A_if1").all()

    def test_no_candidate_dropped(self):
        # no http row at all
        df = _spans([
            ("t1", 0, "0", "(?)", "rpc", "A", "if1", 100),
        ])
        out, stats = detect_entries(df)
        assert len(out) == 0
        assert stats["num_without_entry"] == 1

    def test_http_not_at_min_timestamp_dropped(self):
        df = _spans([
            ("t1", 0, "0", "(?)", "rpc", "A", "if1", 100),
            ("t1", 5, "0.1", "A", "http", "B", "if2", 50),
        ])
        out, _ = detect_entries(df)
        assert len(out) == 0

    def test_tiebreak_on_um(self):
        # two candidates at min ts and max |rt|; only one has um == "(?)"
        df = _spans([
            ("t1", 0, "0", "(?)", "http", "A", "if1", 100),
            ("t1", 0, "0x", "Z", "http", "A", "if9", -100),
            ("t1", 1, "0.1", "A", "rpc", "B", "if2", 50),
        ])
        out, _ = detect_entries(df)
        assert set(out["traceid"]) == {"t1"}
        assert (out["entryid"] == "A_if1").all()

    def test_ambiguous_dropped(self):
        df = _spans([
            ("t1", 0, "0", "(?)", "http", "A", "if1", 100),
            ("t1", 0, "0x", "(?)", "http", "B", "if9", -100),
        ])
        out, stats = detect_entries(df)
        assert len(out) == 0
        assert stats["num_ambiguous_entry"] == 1

    def test_negative_rt_counts_as_max(self):
        # |rt| semantics: -200 beats 100 (preprocess.py:114)
        df = _spans([
            ("t1", 0, "0", "(?)", "http", "A", "if1", -200),
            ("t1", 0, "0b", "A", "http", "B", "if2", 100),
            ("t1", 1, "0.1", "A", "rpc", "B", "if2", 50),
        ])
        out, _ = detect_entries(df)
        assert (out["entryid"] == "A_if1").all()


class TestFilters:
    def test_resource_coverage(self):
        df = _spans([
            # t1: ms {X, A, B} — 2/3 covered >= 0.6 -> keep
            ("t1", 0, "0", "X", "http", "A", "if1", 10),
            ("t1", 1, "1", "A", "rpc", "B", "if2", 5),
            # t2: ms {X, C} — 0/2 covered -> drop
            ("t2", 0, "0", "X", "http", "C", "if1", 10),
        ])
        res = pd.DataFrame({"msname": ["A", "B"]})
        out = filter_by_resource_coverage(df, res)
        assert set(out["traceid"]) == {"t1"}

    def test_entry_occurrence_strictly_greater(self):
        rows = []
        for i in range(5):
            rows.append((f"t{i}", 0, "0", "(?)", "http", "A", "if1", 10))
        rows.append(("u0", 0, "0", "(?)", "http", "B", "if2", 10))
        df = _spans(rows)
        df["entryid"] = np.where(df["dm"] == "A", "A_if1", "B_if2")
        out = filter_by_entry_occurrence(df, IngestConfig(min_traces_per_entry=4))
        assert set(out["entryid"]) == {"A_if1"}  # 5 > 4; 1 <= 4 dropped
        out2 = filter_by_entry_occurrence(df, IngestConfig(min_traces_per_entry=5))
        assert len(out2) == 0  # strict >


def test_factorize_matches_pandas_semantics():
    df = pd.DataFrame({"a": ["x", "y", "x", "z"]})
    out, uniques = factorize_columns(df, ["a"])
    assert out["a"].tolist() == [0, 1, 0, 2]
    assert list(uniques) == ["x", "y", "z"]


def test_resource_table_eight_features():
    res = pd.DataFrame({
        "timestamp": [0, 0, 0, 30_000],
        "msname": ["A", "A", "B", "A"],
        "instance_cpu_usage": [0.1, 0.3, 0.5, 0.7],
        "instance_memory_usage": [0.2, 0.4, 0.6, 0.8],
    })
    table = build_resource_table(res)
    feat_cols = [c for c in table.columns if c not in ("timestamp", "msname")]
    assert len(feat_cols) == 8
    row = table[(table.timestamp == 0) & (table.msname == "A")].iloc[0]
    assert row["instance_cpu_usage_max"] == pytest.approx(0.3)
    assert row["instance_cpu_usage_min"] == pytest.approx(0.1)
    assert row["instance_cpu_usage_mean"] == pytest.approx(0.2)
    assert row["instance_memory_usage_median"] == pytest.approx(0.3)


class TestRealSchemaCSV:
    """Raw-CSV hardening (VERDICT r2 #7): a synthetic-but-real-schema CSV
    tree — unnamed index column, extra columns, NaN string cells, literal
    "nan" strings, "(?)" entries, negative rt, duplicated rows across
    shards — must round-trip through load_raw_csvs + preprocess identically
    to the clean in-memory path."""

    def test_messy_tree_matches_in_memory(self, synth, tmp_path):
        import os

        from pertgnn_tpu.ingest.io import load_raw_csvs

        cfg = IngestConfig(min_traces_per_entry=10)
        want = preprocess(synth.spans, synth.resources, cfg)

        # build the messy tree by hand (not write_csvs): real shards carry
        # an index column and surprises
        cg = tmp_path / "MSCallGraph"
        rs = tmp_path / "MSResource"
        os.makedirs(cg)
        os.makedirs(rs)
        spans = synth.spans.copy()
        # a NaN cell in a string column -> the raw trace's missing marker
        # (normalized to the literal "nan" on load). Use a row that the
        # entry heuristic doesn't touch: um of a non-entry span.
        # synth um values are ms_* or "(?)"; overwrite one duplicate-safe row
        dup_head = spans.iloc[:50].copy()      # duplicated across shards
        shard1 = pd.concat([spans.iloc[:len(spans) // 2], dup_head])
        shard2 = pd.concat([dup_head, spans.iloc[len(spans) // 2:]])
        for i, shard in enumerate((shard1, shard2)):
            shard = shard.copy()
            shard["extra_junk"] = "x"          # column not in the schema
            # unnamed index column, as in the real dataset
            shard.to_csv(cg / f"MSCallGraph_{i}.csv", index=True)
        synth.resources.to_csv(rs / "MSResource_0.csv", index=False)

        spans_l, res_l = load_raw_csvs(str(tmp_path))
        assert list(spans_l.columns) == list(synth.spans.columns)
        got = preprocess(spans_l, res_l, cfg)

        assert got.stats["num_traces_final"] == want.stats["num_traces_final"]
        pd.testing.assert_frame_equal(
            got.spans.sort_values(["traceid", "rpcid"]).reset_index(drop=True),
            want.spans.sort_values(["traceid", "rpcid"]).reset_index(drop=True))
        pd.testing.assert_frame_equal(got.resources, want.resources)
        np.testing.assert_array_equal(got.ms_vocab, want.ms_vocab)

    def test_nan_cells_normalized(self, tmp_path):
        import os

        from pertgnn_tpu.ingest.io import load_raw_csvs

        os.makedirs(tmp_path / "MSCallGraph")
        os.makedirs(tmp_path / "MSResource")
        df = _spans([
            ["t1", 100, "0", "(?)", "http", "A", "if0", 50.0],
            ["t1", 110, "0.1", "A", "rpc", None, "if1", -20.0],  # NaN dm
        ])
        df.to_csv(tmp_path / "MSCallGraph" / "a.csv", index=True)
        # identical resource readings are REAL samples (they shift the
        # mean/median aggregates) — loading must keep both
        pd.DataFrame(
            [[0, "A", 0.5, 0.5], [0, "A", 0.5, 0.5]],
            columns=["timestamp", "msname", "instance_cpu_usage",
                     "instance_memory_usage"],
        ).to_csv(tmp_path / "MSResource" / "r.csv", index=False)
        spans, res = load_raw_csvs(str(tmp_path))
        assert spans["dm"].tolist() == ["A", "nan"]
        assert spans["rt"].tolist() == [50.0, -20.0]
        assert len(res) == 2

    def test_missing_schema_column_raises(self, tmp_path):
        import os

        from pertgnn_tpu.ingest.io import load_raw_csvs

        os.makedirs(tmp_path / "MSCallGraph")
        os.makedirs(tmp_path / "MSResource")
        pd.DataFrame({"traceid": ["t"], "timestamp": [1]}).to_csv(
            tmp_path / "MSCallGraph" / "bad.csv")
        pd.DataFrame(
            [[0, "A", 0.5, 0.5]],
            columns=["timestamp", "msname", "instance_cpu_usage",
                     "instance_memory_usage"],
        ).to_csv(tmp_path / "MSResource" / "r.csv", index=False)
        with pytest.raises(ValueError, match="lacks expected columns"):
            load_raw_csvs(str(tmp_path))


class TestEndToEnd:
    def test_preprocess_synthetic(self, synth, preprocessed):
        pre = preprocessed
        # all factorized columns dense ints from 0
        for col in ("traceid", "um", "dm", "interface", "rpcid", "rpctype",
                    "entryid"):
            vals = pre.spans[col].to_numpy()
            assert np.issubdtype(np.asarray(vals).dtype, np.integer), col
        assert pre.stats["num_traces_final"] > 0
        assert (pre.spans["endTimestamp"]
                >= pre.spans["timestamp"]).all()

    def test_runtime_ids_match_ground_truth(self, synth, preprocessed):
        """Traces generated from the same pattern must share a runtime id."""
        table = assemble(preprocessed)
        tr_vocab = preprocessed.traceid_vocab
        meta = table.meta.set_index("traceid")
        seen = {}
        for tr_code, row in meta.iterrows():
            raw = tr_vocab[tr_code]
            truth = synth.trace_pattern[raw]
            rid = row["runtime_id"]
            if truth in seen:
                assert seen[truth] == rid, f"pattern {truth} split ids"
            else:
                seen[truth] = rid

    def test_labels_are_entry_latency(self, synth, preprocessed):
        table = assemble(preprocessed)
        tr_vocab = preprocessed.traceid_vocab
        raw_spans = synth.spans
        for _, row in table.meta.head(20).iterrows():
            raw_id = tr_vocab[int(row["traceid"])]
            expect = raw_spans[raw_spans.traceid == raw_id]["rt"].abs().max()
            assert row["y"] == pytest.approx(expect)

    def test_mixture_probs_sum_to_one(self, preprocessed):
        table = assemble(preprocessed)
        for entry, (rts, probs) in table.entry2runtimes.items():
            assert probs.sum() == pytest.approx(1.0)
            assert len(rts) == len(set(rts.tolist()))
