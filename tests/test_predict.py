"""Inference path (train/predict.py + cli/predict_main.py): per-trace
predictions aligned to split rows — a capability the reference lacks
entirely (its predictions die inside test()'s metric loop,
pert_gnn.py:254-294)."""

import numpy as np
import pytest

from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import Config, DataConfig, IngestConfig, ModelConfig, TrainConfig
from pertgnn_tpu.models.pert_model import make_model
from pertgnn_tpu.train.loop import evaluate, fit, make_eval_step
from pertgnn_tpu.train.predict import predict_split


@pytest.fixture(scope="module")
def fitted(preprocessed):
    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=200, batch_size=16),
        model=ModelConfig(hidden_channels=16, num_layers=2),
        train=TrainConfig(lr=1e-2, epochs=3, label_scale=1000.0),
        graph_type="pert",
    )
    ds = build_dataset(preprocessed, cfg)
    state, _ = fit(ds, cfg)
    return ds, cfg, state


def test_predictions_aligned_and_consistent_with_eval(fitted):
    """predict_split's per-row predictions must reproduce evaluate()'s
    MAE exactly — both run the same forward; if the row alignment (the
    packer's prefix-order invariant) broke, the internal label check
    raises before this comparison can even run."""
    ds, cfg, state = fitted
    for split in ("valid", "test"):
        pred = predict_split(ds, cfg, state, split)
        y = np.asarray(ds.splits[split].ys, np.float32)
        assert pred.shape == y.shape
        assert np.isfinite(pred).all()
        mae_rows = float(np.abs(pred - y).mean())
        model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                           ds.num_interfaces, ds.num_rpctypes)
        ev = evaluate(make_eval_step(model, cfg), state, ds.batches(split))
        assert mae_rows == pytest.approx(ev["mae"], rel=1e-5)


def test_predictions_carry_signal(fitted):
    """After a few epochs on the signal-bearing synthetic corpus, the
    predictions must beat the trivial constant-mean predictor on the
    TRAIN split (the model demonstrably learned something the rows can
    now carry out of the process)."""
    ds, cfg, state = fitted
    pred = predict_split(ds, cfg, state, "train")
    y = np.asarray(ds.splits["train"].ys, np.float32)
    mae_model = np.abs(pred - y).mean()
    mae_const = np.abs(y.mean() - y).mean()
    assert mae_model < mae_const


@pytest.mark.parametrize("graph_type", ["pert", "span"])
def test_predict_cli_round_trip(tmp_path, graph_type):
    """train_main writes a checkpoint; predict_main restores it and emits
    one aligned CSV row per trace — both graph families."""
    import pandas as pd

    from pertgnn_tpu.cli import predict_main, train_main

    ckpt = str(tmp_path / "ckpt")
    # --artifact_dir keeps the run hermetic: without it both CLIs would
    # use ./processed in the pytest cwd — loading whatever corpus a real
    # run cached there, or poisoning it with this tiny synthetic one
    common = ["--synthetic", "--synthetic_entries", "2",
              "--synthetic_traces_per_entry", "60",
              "--min_traces_per_entry", "5", "--label_scale", "1000",
              "--graph_type", graph_type,
              "--artifact_dir", str(tmp_path / "art"),
              "--checkpoint_dir", ckpt]
    train_main.main([*common, "--epochs", "2"])
    out = str(tmp_path / "preds.csv")
    predict_main.main([*common, "--split", "all", "--out", out])
    df = pd.read_csv(out)
    assert set(df.columns) >= {"traceid", "entry_id", "runtime_id",
                               "ts_bucket", "split", "y_true", "y_pred"}
    assert sorted(df["split"].unique()) == ["test", "train", "valid"]
    assert np.isfinite(df["y_pred"]).all()
    # every trace of the corpus appears exactly once across the splits
    assert df["traceid"].is_unique


def test_predict_from_mesh_trained_checkpoint(preprocessed, tmp_path):
    """Train sharded over a 2-device mesh, predict single-chip from the
    checkpoint: orbax must reshard the mesh-sharded state into the
    single-device restore target (restore_target_state), so distributed
    training composes with local inference."""
    from pertgnn_tpu.parallel.mesh import make_mesh
    from pertgnn_tpu.train.checkpoint import CheckpointManager
    from pertgnn_tpu.train.loop import restore_target_state

    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=200, batch_size=16),
        model=ModelConfig(hidden_channels=8, num_layers=2),
        train=TrainConfig(lr=1e-2, epochs=2, label_scale=1000.0),
        graph_type="pert",
    )
    ds = build_dataset(preprocessed, cfg)
    mesh = make_mesh(data=2, model=1)
    fit(ds, cfg, checkpoint_manager=CheckpointManager(str(tmp_path), keep=2),
        mesh=mesh)

    _model, target = restore_target_state(ds, cfg)
    restored, start = CheckpointManager(str(tmp_path),
                                        keep=2).maybe_restore(target)
    assert start == 2
    pred = predict_split(ds, cfg, restored, "test")
    assert pred.shape == ds.splits["test"].ys.shape
    assert np.isfinite(pred).all()


def test_predict_cli_rejects_mismatched_train_flags(tmp_path, capsys):
    """A label_scale (or arch) differing from the training run restores
    cleanly — tree shapes are blind to semantics — and would silently
    scale every prediction wrong; the config sidecar turns it into an
    error naming the field."""
    from pertgnn_tpu.cli import predict_main, train_main

    ckpt = str(tmp_path / "ckpt")
    common = ["--synthetic", "--synthetic_entries", "2",
              "--synthetic_traces_per_entry", "60",
              "--min_traces_per_entry", "5",
              "--artifact_dir", str(tmp_path / "art"),
              "--checkpoint_dir", ckpt]
    train_main.main([*common, "--label_scale", "1000", "--epochs", "1"])
    with pytest.raises(SystemExit) as e:
        predict_main.main([*common, "--out", str(tmp_path / "p.csv")])
    assert e.value.code == 2
    assert "label_scale" in capsys.readouterr().err
    # matching flags succeed
    predict_main.main([*common, "--label_scale", "1000",
                       "--out", str(tmp_path / "p.csv")])
    assert (tmp_path / "p.csv").exists()


def test_train_cli_resume_rejects_mismatched_flags(tmp_path, capsys):
    """Resume must cross-check the sidecar BEFORE overwriting it — a
    forgotten label_scale at resume would continue training in the wrong
    label space AND launder the sidecar so inference checks pass."""
    from pertgnn_tpu.cli import train_main

    common = ["--synthetic", "--synthetic_entries", "2",
              "--synthetic_traces_per_entry", "60",
              "--min_traces_per_entry", "5",
              "--artifact_dir", str(tmp_path / "art"),
              "--checkpoint_dir", str(tmp_path / "ckpt")]
    train_main.main([*common, "--label_scale", "1000", "--epochs", "1"])
    with pytest.raises(SystemExit) as e:
        train_main.main([*common, "--epochs", "2"])  # flag forgotten
    assert e.value.code == 2
    assert "label_scale" in capsys.readouterr().err
    # explicit override adopts the new flags and proceeds
    train_main.main([*common, "--epochs", "2", "--allow_config_mismatch"])


def test_predict_warns_not_walls_on_sidecar_unknown_field(tmp_path,
                                                          caplog):
    """A sidecar written before a config field existed must WARN, not
    brick every old checkpoint the day a ModelConfig field is added."""
    import json
    import logging

    from pertgnn_tpu.cli import predict_main, train_main

    ckpt = tmp_path / "ckpt"
    common = ["--synthetic", "--synthetic_entries", "2",
              "--synthetic_traces_per_entry", "60",
              "--min_traces_per_entry", "5", "--label_scale", "1000",
              "--artifact_dir", str(tmp_path / "art"),
              "--checkpoint_dir", str(ckpt)]
    train_main.main([*common, "--epochs", "1"])
    sidecar = ckpt / "train_config.json"
    # unwrap the graftvault envelope, drop the field, write back as
    # PLAIN json — simulating an older (pre-graftvault, pre-field)
    # sidecar, which also exercises the legacy-format load fallback
    from pertgnn_tpu.store import durable
    d = durable.read_json(str(sidecar), store="checkpoint")
    del d["model"]["hidden_channels"]  # simulate an older sidecar
    sidecar.write_text(json.dumps(d))
    logging.getLogger("pertgnn_tpu").propagate = True
    with caplog.at_level(logging.WARNING, logger="pertgnn_tpu"):
        predict_main.main([*common, "--out", str(tmp_path / "p.csv")])
    assert (tmp_path / "p.csv").exists()
    assert any("predates config field model.hidden_channels" in r.message
               for r in caplog.records)


def test_predict_cli_requires_checkpoint(tmp_path, capsys):
    from pertgnn_tpu.cli import predict_main

    with pytest.raises(SystemExit) as e:
        predict_main.main(["--synthetic", "--min_traces_per_entry", "5"])
    assert e.value.code == 2
    assert "--checkpoint_dir" in capsys.readouterr().err
    # present flag but empty dir: also a clear error
    with pytest.raises(SystemExit):
        predict_main.main(["--synthetic", "--synthetic_entries", "2",
                           "--synthetic_traces_per_entry", "60",
                           "--min_traces_per_entry", "5",
                           "--artifact_dir", str(tmp_path / "art2"),
                           "--checkpoint_dir", str(tmp_path / "none")])
