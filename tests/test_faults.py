"""Fault injection (pertgnn_tpu/testing/faults.py) and the hardened
request path it validates (serve/queue.py, serve/engine.py,
train/checkpoint.py — docs/RELIABILITY.md).

The load-bearing guarantees:
- a FaultPlan's fire pattern is a pure function of (specs, seed, call
  sequence) — chaos runs are reproducible, not flaky;
- a submitted Future ALWAYS resolves: shed, deadline, quarantine,
  watchdog — every failure is a typed exception, never a hang;
- bisect-retry isolates a poisoned request: innocent co-batched callers
  get predictions BIT-IDENTICAL to a fault-free run;
- a watchdog trip recovers via rebuild and retries the batch once, so a
  transient wedge costs no caller their prediction;
- a corrupt newest checkpoint falls back to the next-oldest preserved
  step instead of crashing the resume path.
"""

import time
from concurrent.futures import TimeoutError as FutureTimeout

import numpy as np
import pytest

from pertgnn_tpu import telemetry
from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                ModelConfig, ServeConfig, TrainConfig)
from pertgnn_tpu.serve.engine import InferenceEngine
from pertgnn_tpu.serve.errors import (DeadlineExceeded, DispatchTimeout,
                                      EngineUnhealthy, QueueClosed,
                                      QueueFull, RequestQuarantined)
from pertgnn_tpu.serve.queue import MicrobatchQueue
from pertgnn_tpu.telemetry import MetricsWriter, TelemetryBus, load_events
from pertgnn_tpu.testing import faults
from pertgnn_tpu.testing.faults import FaultPlan, FaultSpec, InjectedFault
from pertgnn_tpu.train.loop import restore_target_state

# small model + coarse ladder: the fault tests rebuild (recompile) the
# engine several times, so per-rung compile cost dominates runtime
SERVE = ServeConfig(bucket_growth=2.0, min_bucket_nodes=256,
                    min_bucket_edges=256, max_graphs_per_batch=8,
                    dispatch_timeout_s=30.0)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test starts and ends with no armed fault plan."""
    prev = faults.install(None)
    yield
    faults.install(prev)


@pytest.fixture(scope="module")
def served(preprocessed):
    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=200, batch_size=16),
        model=ModelConfig(hidden_channels=8, num_layers=1),
        train=TrainConfig(label_scale=1000.0),
        serve=SERVE,
        graph_type="pert",
    )
    ds = build_dataset(preprocessed, cfg)
    _model, state = restore_target_state(ds, cfg)
    engine = InferenceEngine.from_dataset(ds, cfg, state).warmup()
    return ds, cfg, state, engine


def _solo_preds(ds, engine, idx):
    """Fault-free per-request predictions (each served alone) — the
    bit-identical reference the fault paths must reproduce."""
    s = ds.splits["test"]
    return np.concatenate([
        engine.predict_microbatch(s.entry_ids[i:i + 1],
                                  s.ts_buckets[i:i + 1]) for i in idx])


class TestFaultPlan:
    def test_deterministic_fire_pattern(self):
        specs = [FaultSpec(site="serve.dispatch", kind="nan", nth=(2, 5)),
                 FaultSpec(site="serve.dispatch", kind="wedge", p=0.5,
                           wedge_s=0.0),
                 FaultSpec(site="serve.compile", kind="error")]
        logs = []
        for _ in range(2):
            plan = FaultPlan(specs, seed=7)
            for _i in range(10):
                try:
                    plan.fire("serve.dispatch", entry_ids=[1])
                except InjectedFault:
                    pass
            logs.append(list(plan.fired))
        assert logs[0] == logs[1]
        # the nth spec fired exactly on occurrences 2 and 5
        nans = [(n, k) for _s, n, k in logs[0] if k == "nan"]
        assert nans == [(2, "nan"), (5, "nan")]

    def test_json_round_trip_preserves_pattern(self):
        plan = FaultPlan([FaultSpec(site="serve.dispatch", kind="error",
                                    nth=(3,), entry_id=9, p=0.8)], seed=3)
        clone = FaultPlan.from_json(plan.to_json())
        assert clone.specs == plan.specs and clone.seed == plan.seed

    def test_env_arming(self, monkeypatch):
        plan = FaultPlan([FaultSpec(site="serve.dispatch", kind="nan")])
        monkeypatch.setenv(faults.ENV_VAR, plan.to_json())
        faults.install(None)
        faults._ENV_CHECKED = False  # simulate a fresh process
        armed = faults.active()
        assert armed is not None and armed.specs == plan.specs

    def test_kinds_and_filters(self):
        slept = []
        plan = FaultPlan([
            FaultSpec(site="serve.dispatch", kind="error", entry_id=4),
            FaultSpec(site="serve.dispatch", kind="wedge", wedge_s=1.5),
        ])
        # entry 4 absent: the error spec is skipped, the wedge fires
        assert plan.fire("serve.dispatch", entry_ids=[1, 2],
                         sleep=slept.append) == "wedge"
        assert slept == [1.5]
        with pytest.raises(InjectedFault):
            plan.fire("serve.dispatch", entry_ids=[3, 4])
        # unknown site: nothing ever fires
        assert plan.fire("nope") is None

    def test_rejects_unknown_kind(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultSpec(site="s", kind="explode")


class TestDelayFault:
    """The straggler mode (ISSUE-13 satellite): ``delay`` stalls a
    dispatch and then SUCCEEDS — slow-without-failing, which is what
    hedged dispatch (fleet/router.py) defends against. PR 4 shipped
    error/wedge/nan; a wedge is meant to TRIP the watchdog, a delay
    must stay below it and return correct bits late."""

    def test_delay_sleeps_then_returns_kind(self):
        slept = []
        plan = FaultPlan([FaultSpec(site="serve.dispatch", kind="delay",
                                    delay_s=0.4, nth=(2,))])
        assert plan.fire("serve.dispatch", sleep=slept.append) is None
        assert plan.fire("serve.dispatch",
                         sleep=slept.append) == "delay"
        assert slept == [0.4]
        assert plan.fired == [("serve.dispatch", 2, "delay")]

    def test_delay_round_trips_and_fires_deterministically(self):
        plan = FaultPlan([FaultSpec(site="serve.dispatch", kind="delay",
                                    delay_s=0.25, p=0.5)], seed=11)
        clone = FaultPlan.from_json(plan.to_json())
        assert clone.specs == plan.specs
        logs = []
        for pl in (plan, clone):
            for _ in range(20):
                pl.fire("serve.dispatch", sleep=lambda _s: None)
            logs.append(list(pl.fired))
        assert logs[0] == logs[1] and len(logs[0]) > 0
        assert all(kind == "delay" for _s, _n, kind in logs[0])

    def test_delayed_dispatch_succeeds_bit_identical(self, served):
        ds, _cfg, _state, engine = served
        s = ds.splits["test"]
        ref = _solo_preds(ds, engine, [0])
        faults.install(FaultPlan([FaultSpec(
            site="serve.dispatch", kind="delay", delay_s=0.3,
            nth=(1,))]))
        t0 = time.perf_counter()
        pred = engine.predict_microbatch(s.entry_ids[:1],
                                         s.ts_buckets[:1])
        dt = time.perf_counter() - t0
        faults.install(None)
        # the dispatch STRAGGLED (no error, no watchdog)...
        assert dt >= 0.3
        # ...and still returned exactly the fault-free bits
        np.testing.assert_array_equal(pred, ref)
        assert engine.healthy


class TestQuarantineBisect:
    def test_innocents_survive_a_poisoned_batch_bit_identical(self, served):
        """One persistently-poisoned entry fails every batch containing
        it; bisect must hand every OTHER caller its exact fault-free
        prediction and pin the exception on the poisoned one."""
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        k = min(8, len(s.entry_ids))
        idx = list(range(k))
        solo = _solo_preds(ds, engine, idx)
        poison = int(s.entry_ids[k - 2])  # mid-batch, exercises both halves
        faults.install(FaultPlan([FaultSpec(
            site="serve.dispatch", kind="error", entry_id=poison,
            message="poisoned request")]))
        with MicrobatchQueue(engine, flush_deadline_ms=25,
                             quarantine_threshold=100) as q:
            futs = [q.submit(int(s.entry_ids[i]), int(s.ts_buckets[i]))
                    for i in idx]
            results = []
            for i, f in enumerate(futs):
                if int(s.entry_ids[i]) == poison:
                    with pytest.raises(InjectedFault):
                        f.result(timeout=60)
                    results.append(None)
                else:
                    results.append(f.result(timeout=60))
            assert q.poisoned >= 1
        for i, (got, want) in enumerate(zip(results, solo)):
            if got is not None:
                assert got == float(want), f"request {i} misaligned"

    def test_repeat_offender_is_quarantined_at_submit(self, served):
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        poison = int(s.entry_ids[0])
        faults.install(FaultPlan([FaultSpec(
            site="serve.dispatch", kind="error", entry_id=poison)]))
        with MicrobatchQueue(engine, flush_deadline_ms=1,
                             quarantine_threshold=2) as q:
            for _ in range(2):
                with pytest.raises(InjectedFault):
                    q.predict(poison, int(s.ts_buckets[0]), timeout=60)
            with pytest.raises(RequestQuarantined):
                q.submit(poison, int(s.ts_buckets[0]))
            assert q.quarantine_rejected == 1
            # an innocent entry still serves normally (search every
            # split: the test split can be single-entry)
            other, other_ts = next(
                (int(e), int(t)) for sp in ds.splits.values()
                for e, t in zip(sp.entry_ids, sp.ts_buckets)
                if int(e) != poison)
            assert np.isfinite(q.predict(other, other_ts, timeout=60))
            assert q.stats_dict()["quarantined_entries"] == [poison]


class TestNaNGuard:
    def test_transient_nan_is_quarantined_not_returned(self, served):
        """A NaN batch output must never reach a caller: the batch is
        retried via bisect (the transient fault has been consumed) and
        every caller gets the real, bit-identical prediction."""
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        k = min(6, len(s.entry_ids))
        idx = list(range(k))
        solo = _solo_preds(ds, engine, idx)
        nans0 = engine.nan_outputs
        faults.install(FaultPlan([FaultSpec(
            site="serve.dispatch", kind="nan", nth=(1,))]))
        with MicrobatchQueue(engine, flush_deadline_ms=25) as q:
            futs = [q.submit(int(s.entry_ids[i]), int(s.ts_buckets[i]))
                    for i in idx]
            got = np.asarray([f.result(timeout=60) for f in futs],
                             np.float32)
        assert engine.nan_outputs == nans0 + 1
        assert np.isfinite(got).all()
        np.testing.assert_array_equal(got, solo)


class TestWatchdog:
    def test_transient_wedge_recovers_and_retries(self, served):
        """One dispatch wedges past the timeout: the watchdog trips,
        rebuild recovers the engine, the batch is retried once, and NO
        caller loses a prediction."""
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        k = min(4, len(s.entry_ids))
        idx = list(range(k))
        solo = _solo_preds(ds, engine, idx)
        rebuilds0 = engine.rebuilds
        faults.install(FaultPlan([FaultSpec(
            site="serve.dispatch", kind="wedge", wedge_s=3.0, nth=(1,))]))
        with MicrobatchQueue(engine, flush_deadline_ms=25,
                             dispatch_timeout_s=0.3) as q:
            futs = [q.submit(int(s.entry_ids[i]), int(s.ts_buckets[i]))
                    for i in idx]
            got = np.asarray([f.result(timeout=120) for f in futs],
                             np.float32)
            assert q.watchdog_trips == 1
            assert q.recovered == 1
        np.testing.assert_array_equal(got, solo)
        assert engine.healthy
        assert engine.rebuilds == rebuilds0 + 1

    def test_persistent_wedge_fails_fast_then_heals(self, served):
        """A wedge that outlives the one recovery retry fails the batch
        with a typed error and fail-fasts subsequent batches through the
        cooldown — no future ever hangs — then serves again once the
        fault clears and the cooldown expires."""
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        eid, tsb = int(s.entry_ids[0]), int(s.ts_buckets[0])
        faults.install(FaultPlan([FaultSpec(
            site="serve.dispatch", kind="wedge", wedge_s=2.0)]))
        with MicrobatchQueue(engine, flush_deadline_ms=1,
                             dispatch_timeout_s=0.2) as q:
            with pytest.raises(DispatchTimeout):
                q.predict(eid, tsb, timeout=120)
            assert q.watchdog_trips == 2  # first trip + failed retry
            assert not engine.healthy
            # inside the cooldown: fail fast, not queue-behind-a-wedge
            with pytest.raises(EngineUnhealthy):
                q.predict(eid, tsb, timeout=60)
            faults.install(None)  # transport un-wedges
            time.sleep(q._cooldown_s + 0.1)
            got = q.predict(eid, tsb, timeout=120)
            assert q.recovered >= 1
        assert engine.healthy
        assert np.isfinite(got)


class TestAdmissionAndDeadlines:
    def test_overload_sheds_with_queue_full(self, served):
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        eid, tsb = int(s.entry_ids[0]), int(s.ts_buckets[0])
        with MicrobatchQueue(engine, flush_deadline_ms=10_000,
                             max_pending=3) as q:
            futs = [q.submit(eid, tsb) for _ in range(3)]
            with pytest.raises(QueueFull):
                q.submit(eid, tsb)
            assert q.shed == 1
            # the admitted requests are NOT casualties of the overload:
            # close() drains them to real predictions
        for f in futs:
            assert np.isfinite(f.result(timeout=60))

    def test_request_deadline_resolves_instead_of_waiting(self, served):
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        with MicrobatchQueue(engine, flush_deadline_ms=30_000,
                             request_deadline_ms=50) as q:
            fut = q.submit(int(s.entry_ids[0]), int(s.ts_buckets[0]))
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=10)
            assert q.deadline_exceeded == 1

    def test_predict_timeout_bounds_the_blocking_caller(self, served):
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        with MicrobatchQueue(engine, flush_deadline_ms=30_000) as q:
            t0 = time.perf_counter()
            with pytest.raises(FutureTimeout):
                q.predict(int(s.entry_ids[0]), int(s.ts_buckets[0]),
                          timeout=0.1)
            assert time.perf_counter() - t0 < 5.0

    def test_drain_stops_admissions_but_flushes_in_flight(self, served):
        ds, cfg, _state, engine = served
        s = ds.splits["test"]
        q = MicrobatchQueue(engine, flush_deadline_ms=200)
        try:
            futs = [q.submit(int(s.entry_ids[i]), int(s.ts_buckets[i]))
                    for i in range(min(3, len(s.entry_ids)))]
            q.begin_drain()
            assert q.draining
            with pytest.raises(QueueClosed):
                q.submit(int(s.entry_ids[0]), int(s.ts_buckets[0]))
        finally:
            q.close()
        for f in futs:
            assert np.isfinite(f.result(timeout=60))


class TestCompileFault:
    def test_rung_compile_failure_is_loud(self, served):
        ds, cfg, state, _engine = served
        faults.install(FaultPlan([FaultSpec(site="serve.compile",
                                            kind="error", nth=(1,))]))
        fresh = InferenceEngine.from_dataset(ds, cfg, state)
        with pytest.raises(InjectedFault):
            fresh.warmup()


class TestCheckpointFallback:
    def _state(self, served):
        ds, cfg, state, _engine = served
        return state

    def test_corrupt_newest_step_falls_back(self, served, tmp_path):
        from pertgnn_tpu.train.checkpoint import CheckpointManager

        state = self._state(served)
        writer = MetricsWriter(str(tmp_path / "tele"))
        bus = TelemetryBus(writer, level="trace")
        prev = telemetry.set_bus(bus)
        try:
            # the checkpoint.save/corrupt fault garbles step 1 on disk
            # right after its commit — the torn-write signature
            faults.install(FaultPlan([FaultSpec(
                site="checkpoint.save", kind="corrupt", nth=(2,))]))
            mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=3)
            mgr.save(0, state)
            mgr.save(1, state)
            mgr.wait()
            restored, start_epoch = mgr.maybe_restore(state)
            mgr.close()
        finally:
            telemetry.set_bus(prev)
            bus.close()
        assert start_epoch == 1  # fell back to step 0, not crashed
        names = [e["name"] for e in load_events(writer.path)]
        assert "checkpoint.restore_fallback" in names

    def test_all_steps_corrupt_raises(self, served, tmp_path):
        from pertgnn_tpu.train.checkpoint import CheckpointManager

        state = self._state(served)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=3)
        mgr.save(0, state)
        mgr.wait()
        faults.corrupt_checkpoint_step(str(tmp_path / "ckpt"), 0)
        with pytest.raises(Exception):
            mgr.maybe_restore(state)
        mgr.close()

    def test_corrupt_helper_requires_existing_step(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            faults.corrupt_checkpoint_step(str(tmp_path), 7)


@pytest.mark.slow
def test_sigterm_drain_exits_zero(tmp_path):
    """End-to-end through a REAL serve_main process: SIGTERM mid-stream
    stops admissions, flushes in-flight batches, and exits 0 with
    drained:true — preemption of a serving replica is not a crash.
    (benchmarks/chaos_bench.py asserts the same invariant; this is the
    tier-2 pin. The fast in-process drain semantics are covered by
    TestAdmissionAndDeadlines.test_drain_stops_admissions...)"""
    import json
    import os
    import signal
    import subprocess
    import sys
    import urllib.request

    import pandas as pd

    from pertgnn_tpu.cli import train_main

    ckpt = str(tmp_path / "ckpt")
    art = str(tmp_path / "art")
    common = ["--synthetic", "--synthetic_entries", "2",
              "--synthetic_traces_per_entry", "60",
              "--min_traces_per_entry", "5", "--label_scale", "1000",
              "--artifact_dir", art, "--checkpoint_dir", ckpt]
    train_main.main([*common, "--epochs", "1"])
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import Config, IngestConfig, TrainConfig
    from pertgnn_tpu.ingest.io import load_artifacts
    pre, table = load_artifacts(art)
    ds = build_dataset(pre, Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        train=TrainConfig(label_scale=1000.0)), table)
    s = ds.splits["train"]
    req_csv = str(tmp_path / "req.csv")
    pd.DataFrame({"entry_id": [int(s.entry_ids[0])] * 50_000,
                  "ts_bucket": [int(s.ts_buckets[0])] * 50_000,
                  }).to_csv(req_csv, index=False)
    port = 18000 + (os.getpid() % 2000)
    child = subprocess.Popen(
        [sys.executable, "-m", "pertgnn_tpu.cli.serve_main", *common,
         "--requests", req_csv, "--concurrency", "2",
         "--flush_deadline_ms", "5", "--health_port", str(port),
         "--out", str(tmp_path / "served.csv")],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    deadline = time.monotonic() + 600
    ready = False
    while time.monotonic() < deadline and child.poll() is None:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=2) as r:
                if r.status == 200:
                    ready = True
                    break
        except OSError:
            time.sleep(0.5)
    assert ready, "healthz probe never came up"
    time.sleep(1.0)
    child.send_signal(signal.SIGTERM)
    out, _ = child.communicate(timeout=300)
    assert child.returncode == 0
    stats = json.loads([ln for ln in out.splitlines()
                        if ln.startswith("{")][-1])
    assert stats["drained"] is True
    assert 0 < stats["served"] < 50_000
