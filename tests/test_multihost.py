"""Multi-host distributed training test (SURVEY.md §4 "Distributed",
§5.8): two REAL processes, 2 virtual CPU devices each, gloo collectives.

The workers (tests/multihost_worker.py) run one host-packed sharded train
step on the first global batch — each process materializing only its own
shards and assembling the global arrays with
jax.make_array_from_process_local_data — plus one fit() epoch through the
device-materialized multi-host path. The parent then runs the SAME global
step single-process on its 8 virtual devices (data=4 mesh, same dataset,
same seed) and the metrics must agree: the distributed program is the same
SPMD computation, so this must hold to float tolerance.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import optax
import pytest

import jax

from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import (Config, DataConfig, IngestConfig, ModelConfig,
                                TrainConfig)
from pertgnn_tpu.models.pert_model import make_model
from pertgnn_tpu.parallel.data_parallel import (grouped_batches,
                                                make_sharded_train_step,
                                                shard_batch)
from pertgnn_tpu.parallel.mesh import make_mesh
from pertgnn_tpu.train.loop import create_train_state

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 fake CPU devices")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _worker_cfg(preprocessed):
    # mirror of tests/multihost_worker.py — same dataset on every process
    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=200, batch_size=8),
        model=ModelConfig(hidden_channels=16, num_layers=2),
        train=TrainConfig(lr=1e-3, label_scale=1000.0, scan_chunk=1),
    )
    return build_dataset(preprocessed, cfg), cfg


def _run_workers(nproc: int, base, timeout: int) -> dict:
    """Launch nproc real worker processes (2 virtual devices each) and
    return process 0's metrics. A hung worker is killed along with its
    peers instead of leaking onto the shared single core."""
    out = base / "result.json"
    ckpt = base / "ckpt"  # shared dir: distributed orbax round-trip
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    script = os.path.join(_REPO, "tests", "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, script, str(port), str(pid), str(nproc), str(out),
         str(ckpt)],
        env=env, cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(nproc)]
    try:
        outs = [p.communicate(timeout=timeout)[0].decode() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-4000:]}"
    with open(out) as f:
        return json.load(f)


def _assert_step_matches_single_process(result, preprocessed, n_shards):
    """Distributed step metrics == the same global step run
    single-process on this process's fake devices."""
    ds, cfg = _worker_cfg(preprocessed)
    mesh = make_mesh(data=n_shards, model=1,
                     devices=jax.devices()[:n_shards])
    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = optax.adam(cfg.train.lr)
    glob = next(grouped_batches(ds.batches("train"), n_shards))
    state = create_train_state(model, tx, glob, cfg.train.seed)
    step, sh_state = make_sharded_train_step(model, cfg, tx, mesh, state)
    _, m = step(sh_state, shard_batch(glob, mesh))
    assert result["count"] == float(m["count"])
    for key in ("qloss_sum", "mae_sum", "mape_sum"):
        np.testing.assert_allclose(result[key], float(m[key]),
                                   rtol=1e-4, err_msg=key)


@pytest.fixture(scope="module")
def worker_result(tmp_path_factory):
    """Run the 2-process job once; returns process 0's metrics."""
    return _run_workers(2, tmp_path_factory.mktemp("mh"), timeout=600)


def test_two_process_step_matches_single_process(worker_result, preprocessed):
    """Distributed step metrics == single-process metrics on the same
    global batch (VERDICT r2 #3 'done' criterion)."""
    _assert_step_matches_single_process(worker_result, preprocessed, 4)


def test_two_process_fit_epoch_finite(worker_result):
    """The device-materialized multi-host fit() epoch ran and produced
    finite metrics over the full train split."""
    assert np.isfinite(worker_result["fit_train_qloss"])


def test_two_process_checkpoint_roundtrip(worker_result):
    """Distributed orbax save + sharding-aware restore across 2 real
    processes (both participate; values and shardings preserved)."""
    assert worker_result.get("ckpt_roundtrip") is True


@pytest.mark.skipif(not os.environ.get("RUN_MULTIHOST_4"),
                    reason="opt-in (RUN_MULTIHOST_4=1): 4 real processes "
                           "x 2 virtual devices — heavy on a 1-core host")
def test_four_process_step_matches_single_process(preprocessed,
                                                  tmp_path_factory):
    """Scale-out sanity beyond the 2-process default: 4 REAL processes,
    8 global devices, same SPMD program — step metrics must equal the
    single-process data=8 run."""
    result = _run_workers(4, tmp_path_factory.mktemp("mh4"), timeout=1800)
    _assert_step_matches_single_process(result, preprocessed, 8)
    assert result.get("ckpt_roundtrip") is True
    assert np.isfinite(result["fit_train_qloss"])


def test_host_grouped_batches_single_process_equals_grouped(preprocessed):
    """With one process the per-host pipeline owns ALL shards, so
    host_grouped_batches must equal grouped_batches (up to the edge
    re-sort stack_batches performs; multihost slabs skip it — order-free
    segment attention)."""
    import functools

    from pertgnn_tpu.batching.materialize import zero_masked_idx
    from pertgnn_tpu.parallel.multihost import (host_grouped_batches,
                                                process_shard_slice)

    ds, _ = _worker_cfg(preprocessed)
    assert process_shard_slice(4) == slice(0, 4)
    filler = functools.partial(zero_masked_idx, arena=ds.arena(),
                               feats=ds.feat_arena())
    got = list(host_grouped_batches(ds.index_batches("train"), 4,
                                    ds.materializer("train"), filler))
    want = list(grouped_batches(ds.batches("train"), 4))
    assert len(got) == len(want)
    for g, w in zip(got, want):
        # masks must agree exactly; values only under the mask (the two
        # paths fill inert pad shards differently: zero_masked keeps the
        # cloned batch's values, sentinel recipes materialize zeros)
        np.testing.assert_array_equal(g.node_mask, w.node_mask)
        np.testing.assert_array_equal(g.graph_mask, w.graph_mask)
        nm, gm = g.node_mask, g.graph_mask
        for f in ("x", "ms_id", "node_graph", "pattern_prob"):
            np.testing.assert_array_equal(getattr(g, f)[nm],
                                          getattr(w, f)[nm], err_msg=f)
        for f in ("entry_id", "y"):
            np.testing.assert_array_equal(getattr(g, f)[gm],
                                          getattr(w, f)[gm], err_msg=f)

        def edge_key(b):
            m = b.edge_mask
            cols = np.stack([b.receivers[m], b.senders[m],
                             b.edge_iface[m]])
            return cols[:, np.lexsort(cols)]

        np.testing.assert_array_equal(edge_key(g), edge_key(w))
