"""Persistent arena store (batching/arena_store.py): keying,
bit-identical reconstruction, loud invalidation, corrupt fallback.

Mirrors tests/test_aot.py's structure for the data path. The
load-bearing guarantees:

- a SECOND load over the same (config, fingerprint) performs ZERO
  ingest/graph/featurize work (build_fn never called; arena.cache_hit
  counted) and yields arenas, packed batches, and serve-packed
  microbatches BIT-IDENTICAL to the freshly built dataset;
- ANY drift in a keyed ingredient (ingest knob, data knob, graph type,
  arena-relevant model field, raw-input fingerprint) changes the key —
  replaying stale arenas is impossible by construction, and the miss is
  diagnosed loudly (arena.invalidated + the changed-ingredient log);
- a corrupt/truncated entry falls back to a fresh build with a warning
  — never a crash.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np
import pytest

from pertgnn_tpu import telemetry
from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.batching.arena_store import (ArenaStore, arena_cache_key,
                                              mixtures_from_arena)
from pertgnn_tpu.batching.pack import pack_single
from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                ModelConfig)

FP = {"kind": "test", "seed": 7}


def _cfg(**kw) -> Config:
    base = dict(ingest=IngestConfig(min_traces_per_entry=10),
                data=DataConfig(max_traces=200, batch_size=16),
                model=ModelConfig(hidden_channels=8, num_layers=1),
                graph_type="pert")
    base.update(kw)
    return Config(**base)


class _RecordingBus(telemetry.NoopBus):
    def __init__(self):
        self.events: list[tuple[str, str, dict]] = []

    def counter(self, name, value=1, *, level=1, **tags):
        self.events.append(("counter", name, tags))

    def gauge(self, name, value, *, level=1, **tags):
        self.events.append(("gauge", name, {"value": value, **tags}))

    def histogram(self, name, value, *, level=1, **tags):
        self.events.append(("histogram", name, tags))

    def count(self, name: str) -> int:
        return sum(1 for _, n, _t in self.events if n == name)


@pytest.fixture(scope="module")
def stored(preprocessed, tmp_path_factory):
    """(store root, cfg, fresh dataset) with the arenas persisted once —
    the warm-path tests reload from it."""
    root = str(tmp_path_factory.mktemp("arena_store"))
    cfg = _cfg()
    bus = _RecordingBus()
    store = ArenaStore(root, bus=bus)
    ds = store.load_or_build(cfg, FP,
                             lambda: build_dataset(preprocessed, cfg))
    return root, cfg, ds, bus


class TestKeys:
    def test_key_is_deterministic(self):
        k1, _ = arena_cache_key(_cfg(), FP)
        k2, _ = arena_cache_key(_cfg(), FP)
        assert k1 == k2

    @pytest.mark.parametrize("mutate", [
        lambda c: c.replace(ingest=IngestConfig(min_traces_per_entry=11)),
        lambda c: c.replace(data=dataclasses.replace(c.data,
                                                     max_traces=199)),
        lambda c: c.replace(data=dataclasses.replace(c.data,
                                                     batch_size=17)),
        lambda c: c.replace(graph_type="span"),
        lambda c: c.replace(model=dataclasses.replace(
            c.model, use_node_depth=True)),
        lambda c: c.replace(model=dataclasses.replace(
            c.model, feature_all_stage_copies=True)),
        lambda c: c.replace(model=dataclasses.replace(
            c.model, missing_indicator_is_one=False)),
    ])
    def test_any_arena_ingredient_changes_key(self, mutate):
        base, _ = arena_cache_key(_cfg(), FP)
        changed, _ = arena_cache_key(mutate(_cfg()), FP)
        assert changed != base

    def test_fingerprint_changes_key(self):
        base, _ = arena_cache_key(_cfg(), FP)
        other, _ = arena_cache_key(_cfg(), {**FP, "seed": 8})
        assert other != base

    @pytest.mark.parametrize("mutate", [
        lambda c: c.replace(train=dataclasses.replace(c.train, lr=1e-2)),
        lambda c: c.replace(train=dataclasses.replace(c.train,
                                                      epochs=3)),
        lambda c: c.replace(data=dataclasses.replace(c.data,
                                                     shuffle_seed=5)),
        lambda c: c.replace(model=dataclasses.replace(
            c.model, hidden_channels=64)),
    ])
    def test_arena_irrelevant_knobs_do_not_invalidate(self, mutate):
        """Knobs the arenas never see (optimizer, epoch count, shuffle
        seed, model width) must NOT orphan the cache."""
        base, _ = arena_cache_key(_cfg(), FP)
        same, _ = arena_cache_key(mutate(_cfg()), FP)
        assert same == base


class TestWarmPath:
    def test_second_load_skips_build_entirely(self, stored):
        root, cfg, _ds, _bus = stored
        bus = _RecordingBus()

        def forbidden():
            raise AssertionError("warm hit must not rebuild")

        ds2 = ArenaStore(root, bus=bus).load_or_build(cfg, FP, forbidden)
        assert bus.count("arena.cache_hit") == 1
        assert bus.count("arena.cache_miss") == 0
        assert bus.count("arena.build_seconds") == 0
        mmaps = [e for e in bus.events if e[1] == "arena.mmap_bytes"]
        assert mmaps and mmaps[0][2]["value"] > 0
        assert len(ds2.splits["train"]) > 0

    def test_reconstructed_arenas_bit_identical(self, stored,
                                                preprocessed):
        root, cfg, _ds, _bus = stored
        fresh = build_dataset(preprocessed, cfg)
        warm = ArenaStore(root).load_or_build(
            cfg, FP, lambda: pytest.fail("must hit"))
        for f in dataclasses.fields(fresh.arena()):
            assert np.array_equal(
                np.asarray(getattr(fresh.arena(), f.name)),
                np.asarray(getattr(warm.arena(), f.name))), f.name
        assert np.array_equal(fresh.feat_arena().x, warm.feat_arena().x)
        assert warm.budget == fresh.budget
        assert (warm.num_ms, warm.num_entries, warm.num_interfaces,
                warm.num_rpctypes, warm.node_feature_dim) == (
            fresh.num_ms, fresh.num_entries, fresh.num_interfaces,
            fresh.num_rpctypes, fresh.node_feature_dim)

    def test_warm_epoch_batches_bit_identical(self, stored, preprocessed):
        root, cfg, _ds, _bus = stored
        fresh = build_dataset(preprocessed, cfg)
        warm = ArenaStore(root).load_or_build(
            cfg, FP, lambda: pytest.fail("must hit"))
        for split, shuffle in (("train", True), ("valid", False)):
            a = list(fresh.batches(split, shuffle=shuffle, seed=3))
            b = list(warm.batches(split, shuffle=shuffle, seed=3))
            assert len(a) == len(b) and len(a) > 0
            for x, y in zip(a, b):
                for field in x._fields:
                    assert np.array_equal(getattr(x, field),
                                          getattr(y, field)), field

    def test_reconstructed_mixtures_serve_pack_bit_identical(
            self, stored, preprocessed):
        """The serving request path over arena-reconstructed mixtures
        (receiver-sorted edge order) packs bit-identically to the
        construction-order mixtures: the packer's stable receiver sort
        maps both to the same batch."""
        root, cfg, _ds, _bus = stored
        fresh = build_dataset(preprocessed, cfg)
        warm = ArenaStore(root).load_or_build(
            cfg, FP, lambda: pytest.fail("must hit"))
        recon = mixtures_from_arena(warm.arena())
        assert set(recon) == set(fresh.mixtures)
        s = fresh.splits["train"]
        entries = np.asarray(s.entry_ids[:3], np.int64)
        buckets = np.asarray(s.ts_buckets[:3], np.int64)
        a = pack_single(fresh.mixtures, entries, buckets, fresh.budget,
                        fresh.lookup)
        b = pack_single(recon, entries, buckets, warm.budget, warm.lookup)
        for field in a._fields:
            assert np.array_equal(getattr(a, field),
                                  getattr(b, field)), field


class TestInvalidation:
    def test_changed_ingredient_misses_loudly(self, stored, preprocessed,
                                              caplog):
        root, _cfg0, _ds, _bus = stored
        cfg2 = _cfg(graph_type="span")
        bus = _RecordingBus()
        built = []
        with caplog.at_level("WARNING"):
            ArenaStore(root, bus=bus).load_or_build(
                cfg2, FP, lambda: built.append(1) or build_dataset(
                    preprocessed, cfg2))
        assert built == [1]
        assert bus.count("arena.cache_miss") == 1
        assert bus.count("arena.invalidated") == 1
        assert any("graph_type" in r.message and "invalidating" in
                   r.message for r in caplog.records)

    def test_corrupt_entry_falls_back_to_fresh_build(
            self, preprocessed, tmp_path, caplog):
        root = str(tmp_path / "store")
        cfg = _cfg()
        store = ArenaStore(root, bus=_RecordingBus())
        store.load_or_build(cfg, FP,
                            lambda: build_dataset(preprocessed, cfg))
        key, _ = arena_cache_key(cfg, FP)
        # truncate one array (inside the committed generation dir) to
        # garbage
        victim = os.path.join(store._entry_dir(key), "arena_ms_id.npy")
        with open(victim, "wb") as f:
            f.write(b"\x00garbage")
        bus = _RecordingBus()
        built = []
        with caplog.at_level("WARNING"):
            ds = ArenaStore(root, bus=bus).load_or_build(
                cfg, FP, lambda: built.append(1) or build_dataset(
                    preprocessed, cfg))
        assert built == [1]
        assert bus.count("arena.cache_hit") == 0
        assert any(e[2].get("reason") == "corrupt" for e in bus.events
                   if e[1] == "arena.cache_miss")
        assert any("corrupt arena store entry" in r.message
                   for r in caplog.records)
        # the fresh save overwrote the torn entry: next load hits again
        bus2 = _RecordingBus()
        ArenaStore(root, bus=bus2).load_or_build(
            cfg, FP, lambda: pytest.fail("overwritten entry must hit"))
        assert bus2.count("arena.cache_hit") == 1
        assert len(ds.splits["train"]) > 0

    def test_torn_meta_is_corrupt_not_crash(self, preprocessed, tmp_path):
        root = str(tmp_path / "store")
        cfg = _cfg()
        store = ArenaStore(root)
        store.load_or_build(cfg, FP,
                            lambda: build_dataset(preprocessed, cfg))
        key, _ = arena_cache_key(cfg, FP)
        # tear the MANIFEST (the commit record) — the graftvault torn
        # read surface
        from pertgnn_tpu.store import durable
        with open(durable.manifest_path(root, key), "w") as f:
            f.write('{"trunc')
        built = []
        ArenaStore(root).load_or_build(
            cfg, FP, lambda: built.append(1) or build_dataset(
                preprocessed, cfg))
        assert built == [1]


class TestCLIWiring:
    def test_build_dataset_cached_via_flags(self, tmp_path):
        """The shared CLI helper: cold run builds + persists, warm run
        reconstructs with zero ingest (the raw-input fingerprint comes
        from the synthetic flags)."""
        import argparse

        from pertgnn_tpu.cli.common import (add_aot_flags,
                                            add_ingest_flags,
                                            add_model_train_flags,
                                            build_dataset_cached,
                                            config_from_args)

        p = argparse.ArgumentParser()
        add_ingest_flags(p)
        add_model_train_flags(p)
        add_aot_flags(p)
        argv = ["--synthetic", "--min_traces_per_entry", "10",
                "--synthetic_entries", "3",
                "--synthetic_traces_per_entry", "40",
                "--max_traces", "200", "--batch_size", "16",
                "--hidden_channels", "8", "--graph_type", "pert",
                "--artifact_dir", str(tmp_path / "art"),
                "--arena_cache_dir", str(tmp_path / "arena")]
        args = p.parse_args(argv)
        cfg = config_from_args(args)
        assert cfg.data.arena_cache_dir == str(tmp_path / "arena")
        ds_cold = build_dataset_cached(args, cfg)
        # warm: ingest is unreachable — loading artifacts would fail
        # (none were written; --synthetic ingests in-memory), so a
        # successful reconstruction proves the cache carried everything
        ds_warm = build_dataset_cached(args, cfg)
        assert np.array_equal(
            np.asarray(ds_cold.splits["train"].ys),
            np.asarray(ds_warm.splits["train"].ys))
        a = next(ds_cold.batches("train"))
        b = next(ds_warm.batches("train"))
        for field in a._fields:
            assert np.array_equal(getattr(a, field),
                                  getattr(b, field)), field
