"""The lens subsystem (pertgnn_tpu/lens/ — ISSUE 15).

Layered cheapest-first, like the sibling suites:

1. pure math — quantile-tau resolution (legacy byte-compat rules),
   calibration coverage/monotonicity, the LensRequest wire codec;
2. the NON-CROSSING property under hypothesis: quantile vectors are
   monotone for RANDOM params and inputs (a structural guarantee of the
   cumulative-softplus head, not a training outcome);
3. the counterfactual edit ORACLE: apply_whatif on a built mixture is
   array-identical to build_mixtures over the hand-edited GraphSpec,
   and the edited mixture PACKS bit-identically to packing the edited
   graph from scratch;
4. engine-level attribution: pad rows of the local output are -inf and
   can never be named by top-k;
5. fleet round-trip of the new request-variant fields — including the
   hedged (both legs carry identical lens meta, exactly-once result)
   and shed (typed Shed for a lens request, never lost) paths, with
   injected transports so both race orders are deterministic;
6. AOT key coverage: quantile_taus and local_loss_weight invalidate
   the train/serve program keys.
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.batching.mixture import build_mixtures
from pertgnn_tpu.batching.pack import BatchBudget, pack_single
from pertgnn_tpu.config import (Config, DataConfig, FleetConfig,
                                IngestConfig, LensConfig, ModelConfig,
                                ServeConfig, TrainConfig,
                                primary_tau_index, resolve_quantile_taus)
from pertgnn_tpu.graphs.construct import GraphSpec
from pertgnn_tpu.lens.calibrate import (calibration_errors,
                                        coverage_per_tau,
                                        monotone_violations)
from pertgnn_tpu.lens.request import LensRequest, LensResult
from pertgnn_tpu.lens.whatif import apply_whatif, pattern_blocks
from pertgnn_tpu.serve.errors import (LensDisabled, Shed, WhatIfRefused)

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st
    _HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover — dev extra absent
    _HAVE_HYPOTHESIS = False

_needs_hypothesis = pytest.mark.skipif(
    not _HAVE_HYPOTHESIS, reason="property tests need the hypothesis "
    "dev extra; the deterministic grid twin below always runs")


# --- 1. pure math ---------------------------------------------------------


def test_resolve_taus_legacy_default_follows_train_tau():
    # the byte-compat rule: (0.5,) = legacy mode, train.tau wins
    assert resolve_quantile_taus(ModelConfig(), 0.5) == (0.5,)
    assert resolve_quantile_taus(ModelConfig(), 0.7) == (0.7,)
    m = ModelConfig(quantile_taus=(0.5, 0.95, 0.99))
    assert resolve_quantile_taus(m, 0.7) == (0.5, 0.95, 0.99)
    # a single NON-default level wins over train.tau too
    assert resolve_quantile_taus(
        ModelConfig(quantile_taus=(0.9,)), 0.5) == (0.9,)


def test_resolve_taus_validation():
    with pytest.raises(ValueError):
        resolve_quantile_taus(ModelConfig(quantile_taus=()), 0.5)
    with pytest.raises(ValueError):
        resolve_quantile_taus(
            ModelConfig(quantile_taus=(0.9, 0.5)), 0.5)  # not ascending
    with pytest.raises(ValueError):
        resolve_quantile_taus(
            ModelConfig(quantile_taus=(0.5, 0.5)), 0.5)  # not strict
    with pytest.raises(ValueError):
        resolve_quantile_taus(
            ModelConfig(quantile_taus=(0.1, 1.5)), 0.5)  # out of (0,1)


def test_primary_tau_index():
    assert primary_tau_index((0.5, 0.95, 0.99), 0.5) == 0
    assert primary_tau_index((0.1, 0.5, 0.9), 0.5) == 1
    assert primary_tau_index((0.9, 0.95), 0.5) == 0


def test_coverage_and_monotone_math():
    y = np.array([1.0, 2.0, 3.0, 4.0])
    preds = np.array([[0.5, 5.0]] * 4)  # q0 under everything, q1 over
    cov = coverage_per_tau(y, preds)
    assert cov.tolist() == [0.0, 1.0]
    errs = calibration_errors(y, preds, (0.5, 0.9))
    assert errs.tolist() == [0.5, pytest.approx(0.1)]
    assert monotone_violations(preds) == 0
    bad = np.array([[1.0, 0.5], [1.0, 2.0]])
    assert monotone_violations(bad) == 1
    # scalar predictions: trivially monotone, coverage still defined
    assert monotone_violations(np.array([1.0, 2.0])) == 0
    with pytest.raises(ValueError):
        coverage_per_tau(np.zeros(0), np.zeros((0, 2)))
    with pytest.raises(ValueError):
        calibration_errors(y, preds, (0.5,))  # column/tau mismatch


def test_lens_request_wire_roundtrip():
    assert LensRequest().to_wire() is None
    assert LensRequest.from_wire(None) is None
    r = LensRequest(attribute_k=3,
                    edits=({"op": "drop_edge", "edge": 1},))
    w = r.to_wire()
    assert w == {"k": 3, "edits": [{"op": "drop_edge", "edge": 1}]}
    back = LensRequest.from_wire(w)
    assert back.attribute_k == 3 and back.edits == r.edits
    # edits-only and k-only both omit the other field
    assert LensRequest(edits=({"op": "x"},)).to_wire() == {
        "edits": [{"op": "x"}]}
    assert LensRequest(attribute_k=2).to_wire() == {"k": 2}


# --- 2. the non-crossing property (hypothesis) ----------------------------


def _tiny_batch(rng, n_feat=4):
    from pertgnn_tpu.batching.pack import PackedBatch

    N, E, G = 8, 10, 3
    return PackedBatch(
        x=rng.normal(size=(N, n_feat)).astype(np.float32) * 3,
        ms_id=rng.integers(0, 5, N).astype(np.int32),
        node_depth=np.zeros(N, np.float32),
        node_graph=np.array([0, 0, 0, 1, 1, 2, 2, 2], np.int32),
        node_mask=np.array([1, 1, 1, 1, 1, 1, 0, 0], bool),
        pattern_prob=np.ones(N, np.float32),
        pattern_size=np.ones(N, np.float32),
        senders=rng.integers(0, 6, E).astype(np.int32),
        receivers=rng.integers(0, 6, E).astype(np.int32),
        edge_iface=rng.integers(0, 3, E).astype(np.int32),
        edge_rpctype=rng.integers(0, 2, E).astype(np.int32),
        edge_duration=np.zeros(E, np.float32),
        edge_mask=np.ones(E, bool),
        entry_id=np.array([0, 1, 0], np.int32),
        y=np.zeros(3, np.float32),
        graph_mask=np.array([1, 1, 1], bool))


def _assert_noncrossing(param_seed: int, data_seed: int) -> None:
    import jax

    from pertgnn_tpu.models.pert_model import make_model

    cfg = ModelConfig(hidden_channels=8, num_layers=1,
                      quantile_taus=(0.1, 0.5, 0.9))
    model = make_model(cfg, 5, 2, 3, 2)
    batch = _tiny_batch(np.random.default_rng(data_seed))
    variables = model.init(jax.random.PRNGKey(param_seed), batch,
                           training=False)
    pred, _ = model.apply(variables, batch, training=False)
    assert pred.shape == (3, 3)
    assert monotone_violations(np.asarray(pred)) == 0


if _HAVE_HYPOTHESIS:
    @_needs_hypothesis
    @settings(max_examples=10, deadline=None)
    @given(param_seed=st.integers(0, 2**31 - 1),
           data_seed=st.integers(0, 2**31 - 1))
    def test_noncrossing_property_random_params_and_inputs(param_seed,
                                                           data_seed):
        """Quantile vectors are monotone for ANY parameters and inputs
        — the cumulative-softplus head makes crossing impossible by
        construction, so this needs no training to hold."""
        _assert_noncrossing(param_seed, data_seed)


def test_noncrossing_grid_twin():
    """Deterministic twin of the hypothesis property (always runs)."""
    for param_seed, data_seed in ((0, 0), (1, 7), (12345, 999),
                                  (2**31 - 1, 3)):
        _assert_noncrossing(param_seed, data_seed)


def test_single_tau_head_shape_is_legacy():
    """quantile_taus=(0.5,) keeps the exact pre-lens head: Dense(1)
    kernel shape and a (G,)-shaped prediction — checkpoints and
    compiled programs stay byte-identical."""
    import jax

    from pertgnn_tpu.models.pert_model import make_model

    model = make_model(ModelConfig(hidden_channels=8, num_layers=1),
                       5, 2, 3, 2)
    batch = _tiny_batch(np.random.default_rng(0))
    v = model.init(jax.random.PRNGKey(0), batch, training=False)
    assert v["params"]["global_head2"]["kernel"].shape == (8, 1)
    pred, local = model.apply(v, batch, training=False)
    assert pred.shape == (3,) and local.shape == (8,)


# --- 3. the counterfactual edit oracle ------------------------------------


def _spec(nn, edges, ms, depth=None):
    s = np.array([e[0] for e in edges], np.int32)
    r = np.array([e[1] for e in edges], np.int32)
    ea = np.array([[e[2], e[3]] for e in edges],
                  np.int32).reshape(-1, 2)
    return GraphSpec(
        senders=s, receivers=r, edge_attr=ea,
        ms_id=np.array(ms, np.int32),
        node_depth=np.asarray(depth if depth is not None
                              else np.zeros(nn), np.float32),
        num_nodes=nn, edge_durations=None)


@pytest.fixture()
def mixture_pair():
    """(full mixture, builder) over two patterns: a 3-node chain and a
    2-node pair, mixture-weighted 0.7/0.3."""
    g0 = _spec(3, [(0, 1, 5, 0), (1, 2, 6, 1)], [10, 11, 10],
               [0, .5, 1])
    g1 = _spec(2, [(0, 1, 7, 0)], [12, 10], [0, 1])
    e2r = {0: (np.array([0, 1]), np.array([0.7, 0.3], np.float32))}

    def build(graphs):
        return build_mixtures(graphs, e2r)[0]

    return build({0: g0, 1: g1}), build, g1


def _assert_mixture_equal(a, b, skip=()):
    for f in dataclasses.fields(a):
        if f.name in skip:
            continue
        assert np.array_equal(getattr(a, f.name), getattr(b, f.name)), \
            f.name


def test_whatif_drop_edge_matches_from_scratch(mixture_pair):
    full, build, g1 = mixture_pair
    got = apply_whatif(full, [{"op": "drop_edge", "edge": 1}])
    oracle = build({0: _spec(3, [(0, 1, 5, 0)], [10, 11, 10],
                             [0, .5, 1]), 1: g1})
    _assert_mixture_equal(oracle, got)


def test_whatif_drop_node_matches_from_scratch(mixture_pair):
    full, build, g1 = mixture_pair
    got = apply_whatif(full, [{"op": "drop_node", "node": 1}])
    # documented semantics: node_depth keeps the OBSERVED values
    oracle = build({0: _spec(2, [], [10, 10], [0, 1]), 1: g1})
    _assert_mixture_equal(oracle, got, skip=("node_depth",))
    assert np.array_equal(got.node_depth,
                          np.array([0, 1, 0, 1], np.float32))
    # the pattern block layout is still recoverable
    assert pattern_blocks(got) == [(0, 2), (2, 4)]


def test_whatif_sub_node_recomputes_feature_mask(mixture_pair):
    full, build, g1 = mixture_pair
    got = apply_whatif(full, [{"op": "sub_node", "node": 2,
                               "ms_id": 11}])
    oracle = build({0: _spec(3, [(0, 1, 5, 0), (1, 2, 6, 1)],
                             [10, 11, 11], [0, .5, 1]), 1: g1})
    _assert_mixture_equal(oracle, got)


def test_whatif_sub_edge(mixture_pair):
    full, build, g1 = mixture_pair
    got = apply_whatif(full, [{"op": "sub_edge", "edge": 0, "iface": 9,
                               "rpctype": 1}])
    oracle = build({0: _spec(3, [(0, 1, 9, 1), (1, 2, 6, 1)],
                             [10, 11, 10], [0, .5, 1]), 1: g1})
    _assert_mixture_equal(oracle, got)


def test_whatif_refusals(mixture_pair):
    full, _build, _g1 = mixture_pair
    cases = [
        [{"op": "nope"}],
        [{"op": "drop_edge", "edge": 99}],
        [{"op": "drop_edge", "edge": -1}],
        [{"op": "sub_edge", "edge": 0}],           # no field to set
        [{"op": "drop_node", "node": 4},           # then last of pattern
         {"op": "drop_node", "node": 3}],
        "not-a-dict-list",
    ]
    for edits in cases:
        with pytest.raises((WhatIfRefused, TypeError)):
            apply_whatif(full, edits
                         if isinstance(edits, list) else [edits])
    with pytest.raises(WhatIfRefused):
        apply_whatif(full, [{"op": "sub_node", "node": 0, "ms_id": 99}],
                     num_ms=13)
    with pytest.raises(WhatIfRefused):
        apply_whatif(full, [{"op": "sub_edge", "edge": 0, "iface": 50}],
                     num_interfaces=13)
    # the input is never mutated by a refused (or successful) edit
    assert full.num_nodes == 5 and full.num_edges == 3


def test_whatif_never_grows(mixture_pair):
    full, _build, _g1 = mixture_pair
    for edits in ([{"op": "drop_edge", "edge": 0}],
                  [{"op": "drop_node", "node": 1}],
                  [{"op": "sub_node", "node": 0, "ms_id": 1}]):
        out = apply_whatif(full, edits)
        assert out.num_nodes <= full.num_nodes
        assert out.num_edges <= full.num_edges


# --- engine-level: pack bit-identity + attribution pad exclusion ----------


@pytest.fixture(scope="module")
def lens_served(preprocessed):
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.train.loop import restore_target_state

    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=200, batch_size=16),
        model=ModelConfig(hidden_channels=8, num_layers=1,
                          local_loss_weight=0.1),
        train=TrainConfig(label_scale=1000.0),
        serve=ServeConfig(bucket_growth=2.0, min_bucket_nodes=256,
                          min_bucket_edges=256, max_graphs_per_batch=8),
        lens=LensConfig(lens_local=True, lens_top_k=4),
        graph_type="pert",
    )
    ds = build_dataset(preprocessed, cfg)
    _model, state = restore_target_state(ds, cfg)
    engine = InferenceEngine.from_dataset(ds, cfg, state).warmup()
    return ds, cfg, state, engine


def test_edited_pack_bit_identical_to_from_scratch(lens_served):
    """The acceptance oracle: packing an edited mixture through
    mixture_of is bit-identical to packing the same edited mixture
    registered as the entry's base — the override changes WHICH arrays
    pack, nothing about HOW."""
    ds, cfg, _state, _engine = lens_served
    eid = int(ds.splits["test"].entry_ids[0])
    tsb = int(ds.splits["test"].ts_buckets[0])
    mix = ds.mixtures[eid]
    assert mix.num_edges > 0
    edited = apply_whatif(mix, [{"op": "drop_edge", "edge": 0}])
    budget = BatchBudget(max_graphs=4, max_nodes=256, max_edges=256)
    via_override = pack_single(
        ds.mixtures, np.array([eid]), np.array([tsb]), budget,
        ds.lookup, mixture_of=[edited])
    scratch_mixtures = dict(ds.mixtures)
    scratch_mixtures[eid] = edited
    from_scratch = pack_single(
        scratch_mixtures, np.array([eid]), np.array([tsb]), budget,
        ds.lookup)
    for field, a, b in zip(via_override._fields, via_override,
                           from_scratch):
        assert np.array_equal(a, b), field


def test_attribution_pads_unrankable(lens_served):
    """Pad rows of the local output are -inf (pinned in-graph) and the
    attribution rows can only name real nodes; k past the node count
    truncates."""
    ds, cfg, _state, engine = lens_served
    s = ds.splits["test"]
    eid, tsb = int(s.entry_ids[0]), int(s.ts_buckets[0])
    mix = ds.mixtures[eid]
    packed = engine.pack_microbatch([eid], [tsb], want_local=True)
    preds = engine.complete_microbatch(engine.dispatch_packed(packed))
    assert len(preds) == 1
    nm = np.asarray(packed.batch.node_mask)
    assert np.isneginf(packed.local[~nm]).all()
    assert np.isfinite(packed.local[nm]).all()
    rows = engine.attribution_rows(packed, 0, 100, mix)
    # k clamped by lens_top_k (4) and the mixture's node count
    assert len(rows) == min(4, mix.num_nodes)
    for r in rows:
        assert 0 <= r["node"] < mix.num_nodes
        assert np.isfinite(r["local"])
    locals_ = [r["local"] for r in rows]
    assert locals_ == sorted(locals_, reverse=True)


def test_lens_disabled_refused_at_submit(lens_served):
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.serve.queue import MicrobatchQueue
    from pertgnn_tpu.train.loop import restore_target_state

    ds, cfg, state, _engine = lens_served
    cfg_off = dataclasses.replace(cfg, lens=LensConfig(lens_local=False))
    engine = InferenceEngine.from_dataset(ds, cfg_off, state).warmup()
    s = ds.splits["test"]
    with MicrobatchQueue(engine, flush_deadline_ms=0.0) as q:
        with pytest.raises(LensDisabled):
            q.submit(int(s.entry_ids[0]), int(s.ts_buckets[0]),
                     lens=LensRequest(attribute_k=1))
        # plain traffic unaffected
        assert isinstance(q.predict(int(s.entry_ids[0]),
                                    int(s.ts_buckets[0])), float)


def test_queue_mixed_lens_traffic_resolves(lens_served):
    """Attribution, what-if, and plain requests interleaved through one
    queue: every future resolves to its own variant's result type, and
    the edited request's prediction rides the same coalescing
    machinery (local-homogeneous batching)."""
    from pertgnn_tpu.serve.queue import MicrobatchQueue

    ds, cfg, _state, engine = lens_served
    s = ds.splits["test"]
    eid, tsb = int(s.entry_ids[0]), int(s.ts_buckets[0])
    with MicrobatchQueue(engine, flush_deadline_ms=2.0) as q:
        futs = [
            q.submit(eid, tsb),
            q.submit(eid, tsb, lens=LensRequest(attribute_k=2)),
            q.submit(eid, tsb, lens=LensRequest(
                edits=({"op": "drop_edge", "edge": 0},))),
            q.submit(eid, tsb),
        ]
        plain = futs[0].result(60)
        attr = futs[1].result(60)
        what = futs[2].result(60)
        plain2 = futs[3].result(60)
    assert isinstance(plain, float) and plain == plain2
    assert isinstance(attr, LensResult)
    assert float(np.asarray(attr.pred)) == plain  # same topology
    assert 1 <= len(attr.attribution) <= 2
    assert isinstance(what, float)


# --- 5. fleet round-trip of the lens request fields -----------------------


def _probe_200(base_url, timeout_s):
    return 200, {"ready": True}


def _lens_rows(entries, lens=None):
    rows = []
    lens = lens or [None] * len(entries)
    for e, ln in zip(entries, lens):
        row = {"pred": float(e) * 2.0}
        if isinstance(ln, dict) and ln.get("k"):
            row["attr"] = [{"node": 0, "ms_id": 1, "iface": None,
                            "local": 1.5}]
        rows.append(row)
    return rows


def test_fleet_lens_fields_ride_the_wire_and_back():
    """submit(lens=...) serializes to the transport body (omitted for
    plain traffic) and the worker's attr rows rehydrate to a
    LensResult."""
    from pertgnn_tpu.fleet.router import FleetRouter

    seen = []

    def post(base_url, entries, ts, timeout_s, trace=None, slo=None,
             dg=None, lens=None):
        seen.append(lens)
        return _lens_rows(entries, lens)

    cfg = FleetConfig(router_flush_deadline_ms=0.0,
                      health_poll_interval_s=60.0)
    with FleetRouter({"w": "http://w"}, lambda e: (2, 1), (8, 512, 512),
                     cfg=cfg, transport_post=post,
                     transport_probe=_probe_200) as router:
        f_lens = router.submit(
            3, 0, lens=LensRequest(attribute_k=2,
                                   edits=({"op": "drop_edge",
                                           "edge": 0},)))
        res = f_lens.result(10)
        f_plain = router.submit(4, 0)
        assert f_plain.result(10) == 8.0
    assert isinstance(res, LensResult)
    assert res.pred == 6.0 and res.attribution[0]["ms_id"] == 1
    # first batch carried the wire dict, the plain one omitted lens
    # entirely (the kwarg itself is omit-when-default)
    lens_batches = [x for x in seen if x is not None]
    assert lens_batches and lens_batches[0][0] == {
        "k": 2, "edits": [{"op": "drop_edge", "edge": 0}]}
    assert seen[-1] is None


def test_fleet_lens_hedged_both_legs_identical_meta():
    """A hedged lens dispatch: BOTH legs carry the identical lens wire
    form, the future resolves exactly once to a LensResult, and the
    loser's answer is ignored."""
    from pertgnn_tpu.fleet.router import FleetRouter

    release_primary = threading.Event()
    calls = []
    lock = threading.Lock()

    def post(base_url, entries, ts, timeout_s, trace=None, slo=None,
             dg=None, lens=None):
        with lock:
            calls.append((base_url, lens))
            nth = len(calls)
        if nth == 1:
            assert release_primary.wait(10.0)  # hedge wins
        return _lens_rows(entries, lens)

    cfg = FleetConfig(hedge_quantile_ms=30.0,
                      router_flush_deadline_ms=0.0,
                      health_poll_interval_s=60.0,
                      dispatch_timeout_s=10.0)
    with FleetRouter({"wa": "http://a", "wb": "http://b"},
                     lambda e: (2, 1), (8, 512, 512), cfg=cfg,
                     transport_post=post,
                     transport_probe=_probe_200) as router:
        fut = router.submit(5, 0, lens=LensRequest(attribute_k=1))
        res = fut.result(10.0)
        release_primary.set()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with router._lock:
                if len(calls) >= 2 and router._inflight_legs == 0:
                    break
            time.sleep(0.01)
        stats = router.stats_dict()
    assert isinstance(res, LensResult) and res.pred == 10.0
    assert stats["hedge_fired"] == 1 and stats["hedge_won"] == 1
    assert len(calls) == 2
    # the load-bearing bit: both legs saw the SAME lens meta
    assert calls[0][1] == calls[1][1] == [{"k": 1}]
    assert fut.result() is res  # exactly-once


def test_fleet_lens_request_shed_is_typed_not_lost():
    """A lens request shed at a full router pending set resolves with
    the typed Shed like any other request — the variant fields never
    cost the ALWAYS-resolves contract."""
    from pertgnn_tpu.fleet.router import FleetRouter

    hold = threading.Event()

    def post(base_url, entries, ts, timeout_s, trace=None, slo=None,
             dg=None, lens=None):
        hold.wait(10.0)
        return _lens_rows(entries, lens)

    cfg = FleetConfig(max_pending=1, worker_slots=1,
                      router_flush_deadline_ms=1000.0,
                      health_poll_interval_s=60.0,
                      dispatch_timeout_s=10.0)
    with FleetRouter({"w": "http://w"}, lambda e: (2, 1), (1, 512, 512),
                     cfg=cfg, transport_post=post,
                     transport_probe=_probe_200) as router:
        first = router.submit(1, 0, lens=LensRequest(attribute_k=1))
        # the pending set (size 1) is now occupied; same-class arrivals
        # shed with the typed error
        with pytest.raises(Shed):
            for _ in range(50):
                router.submit(2, 0, lens=LensRequest(attribute_k=1))
                time.sleep(0.01)
        hold.set()
        assert isinstance(first.result(10.0), LensResult)


# --- 6. AOT key coverage --------------------------------------------------


def test_quantile_and_local_weight_ride_the_aot_keys():
    """ModelConfig.quantile_taus and local_loss_weight invalidate the
    train/serve program keys (they change the compiled loss/head), via
    the model subtree riding the key whole."""
    from pertgnn_tpu import aot

    def key_for(model_cfg):
        k, _c = aot.cache_key(fn_id="test.lens.v1",
                              config={"model": model_cfg},
                              args_sig="sig")
        return k

    base = key_for(ModelConfig())
    assert key_for(ModelConfig(quantile_taus=(0.5, 0.9))) != base
    assert key_for(ModelConfig(local_loss_weight=0.1)) != base
    assert key_for(ModelConfig()) == base


def test_serve_rung_key_distinguishes_local_variant(lens_served):
    ds, cfg, _state, engine = lens_served
    name_std, key_std, _c, _a = engine._rung_entry(0, local=False)
    name_loc, key_loc, _c2, _a2 = engine._rung_entry(0, local=True)
    assert name_std != name_loc
    assert key_std != key_loc
