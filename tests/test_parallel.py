"""Distributed tests on the 8-fake-CPU-device mesh (SURVEY.md §4).

The key equivalences:
- sharded data-parallel training == single-device training on the same
  global batch (one SPMD program, so this must hold to float tolerance);
- edge-sharded attention == unsharded segment attention;
- tensor-parallel (2D mesh) training step compiles and runs.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import Config, DataConfig, IngestConfig, ModelConfig, TrainConfig
from pertgnn_tpu.models.pert_model import make_model
from pertgnn_tpu.parallel.data_parallel import (
    grouped_batches,
    make_sharded_eval_step,
    make_sharded_train_step,
    shard_batch,
    stack_batches,
)
from pertgnn_tpu.parallel.mesh import make_mesh
from pertgnn_tpu.train.loop import create_train_state, make_train_step

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 fake CPU devices")


@pytest.fixture(scope="module")
def cfg():
    return Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=200, batch_size=8),
        model=ModelConfig(hidden_channels=16, num_layers=2),
        train=TrainConfig(lr=1e-3, label_scale=1000.0),
    )


@pytest.fixture(scope="module")
def ds(preprocessed, cfg):
    return build_dataset(preprocessed, cfg)


def _setup(ds, cfg, mesh):
    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = optax.adam(cfg.train.lr)
    sample = stack_batches([next(ds.batches("train"))] * mesh.shape["data"])
    state = create_train_state(model, tx, sample, cfg.train.seed)
    return model, tx, state, sample


class TestDataParallel:
    def test_dp_equals_single_device(self, ds, cfg):
        """Sharded gradients == single-device gradients on the same global
        batch. (Comparing post-Adam params is ill-conditioned: the first
        Adam step is ~lr*sign(g), so float reduction-order noise on
        near-zero gradients flips whole entries.)"""
        from pertgnn_tpu.parallel.mesh import batch_shardings, state_shardings
        from pertgnn_tpu.train.loop import _loss_fn

        mesh = make_mesh(data=8, model=1)
        model, tx, state, _ = _setup(ds, cfg, mesh)

        batches = list(ds.batches("train"))[:8]
        global_batch = stack_batches(batches)

        def grads_of(state, batch):
            rng = jax.random.PRNGKey(0)
            return jax.grad(
                lambda p: _loss_fn(model, cfg, p, state.batch_stats, batch,
                                   rng)[0])(state.params)

        g1 = jax.jit(grads_of)(state, jax.tree.map(jnp.asarray, global_batch))
        st_sh = state_shardings(state, mesh)
        g2 = jax.jit(grads_of,
                     in_shardings=(st_sh, batch_shardings(mesh)))(
            jax.device_put(state, st_sh), shard_batch(global_batch, mesh))
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b),
                rtol=1e-4, atol=1e-6 + 1e-4 * np.abs(np.asarray(a)).max()),
            g1, jax.device_get(g2))

        # and the sharded step itself runs + reports identical metrics
        sharded_step, sh_state = make_sharded_train_step(
            model, cfg, tx, mesh, state)
        s2, m2 = sharded_step(sh_state, shard_batch(global_batch, mesh))
        single_step = make_train_step(model, cfg, tx)
        s1, m1 = single_step(jax.tree.map(jnp.copy, state),
                             jax.tree.map(jnp.asarray, global_batch))
        np.testing.assert_allclose(float(m1["mae_sum"]), float(m2["mae_sum"]),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(m1["qloss_sum"]),
                                   float(m2["qloss_sum"]), rtol=1e-5)

    def test_dp_eval_matches(self, ds, cfg):
        from pertgnn_tpu.parallel.mesh import state_shardings

        mesh = make_mesh(data=8, model=1)
        model, tx, state, _ = _setup(ds, cfg, mesh)
        ev = make_sharded_eval_step(model, cfg, mesh, state)
        sh_state = jax.device_put(state, state_shardings(state, mesh))
        total = 0
        for global_batch in grouped_batches(ds.batches("valid"), 8):
            m = ev(sh_state, shard_batch(global_batch, mesh))
            total += int(m["count"])
        assert total == len(ds.splits["valid"])

    def test_grouped_batches_pads_tail(self, ds, cfg):
        n = sum(1 for _ in ds.batches("train"))
        groups = list(grouped_batches(ds.batches("train"), 3))
        assert len(groups) == -(-n // 3)
        total = sum(int(g.graph_mask.sum()) for g in groups)
        assert total == len(ds.splits["train"])


class TestTensorParallel:
    def test_2d_mesh_step_runs(self, ds, cfg):
        mesh = make_mesh(data=4, model=2)
        model, tx, state, sample = _setup(ds, cfg, mesh)
        step, sh_state = make_sharded_train_step(model, cfg, tx, mesh, state)
        for _ in range(2):
            sh_state, m = step(sh_state, shard_batch(sample, mesh))
        assert np.isfinite(float(m["qloss_sum"]))
        # params really are sharded over the model axis
        kernel = sh_state.params["conv_0"]["query"]["kernel"]
        assert len(kernel.sharding.device_set) >= 2


class TestEdgeSharding:
    def test_matches_unsharded(self):
        from pertgnn_tpu.ops.segment import segment_softmax, segment_sum
        from pertgnn_tpu.parallel.graph_shard import sharded_edge_attention

        rng = np.random.default_rng(0)
        N, E, H, C = 64, 512, 2, 8
        q = jnp.asarray(rng.normal(size=(N, H, C)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(N, H, C)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(N, H, C)), jnp.float32)
        e = jnp.asarray(rng.normal(size=(E, H, C)), jnp.float32)
        snd = jnp.asarray(rng.integers(0, N, E), jnp.int32)
        rcv = jnp.asarray(rng.integers(0, N, E), jnp.int32)
        msk = jnp.asarray(rng.random(E) < 0.9)

        mesh = make_mesh(data=8, model=1)
        got = sharded_edge_attention(q, k, v, e, snd, rcv, msk, mesh)

        # unsharded oracle
        k_e = k[snd] + e
        v_e = v[snd] + e
        scores = (q[rcv] * k_e).sum(-1) / np.sqrt(C)
        alpha = segment_softmax(scores, rcv, N, mask=msk)
        want = segment_sum((v_e * alpha[..., None]).reshape(E, -1), rcv, N)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=1e-5)

    def test_giant_graph_5k_nodes(self):
        """BASELINE config 5 shape: a 5k-node DAG, edges sharded 8 ways."""
        from pertgnn_tpu.parallel.graph_shard import sharded_edge_attention

        rng = np.random.default_rng(1)
        N, E, H, C = 5000, 20_000, 1, 32
        q = jnp.asarray(rng.normal(size=(N, H, C)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(N, H, C)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(N, H, C)), jnp.float32)
        e = jnp.asarray(rng.normal(size=(E, H, C)), jnp.float32)
        snd = jnp.asarray(rng.integers(0, N, E), jnp.int32)
        rcv = jnp.asarray(rng.integers(0, N, E), jnp.int32)
        msk = jnp.ones(E, bool)
        mesh = make_mesh(data=8, model=1)
        out = sharded_edge_attention(q, k, v, e, snd, rcv, msk, mesh)
        assert out.shape == (N, H * C)
        assert np.isfinite(np.asarray(out)).all()


def test_fit_with_mesh(ds, cfg):
    """Distributed fit end-to-end on the fake 8-device mesh (with the
    default device_materialize=True this exercises the indexed SPMD
    path)."""
    from pertgnn_tpu.train.loop import fit

    mesh = make_mesh(data=8, model=1)
    state, history = fit(ds, cfg, epochs=2, mesh=mesh)
    assert len(history) == 2
    assert history[1]["train_qloss"] < history[0]["train_qloss"]
    for k, v in history[-1].items():
        assert np.isfinite(v), (k, v)


def test_fit_with_mesh_staged_equals_streamed(ds, cfg):
    """Sharded epoch-staging (one device_put per epoch, device-side
    per-chunk slices — _staged_epoch_iter_sharded) must reproduce the
    per-chunk shard_batch trajectory exactly on the mesh compact path."""
    import dataclasses

    from pertgnn_tpu.train.loop import fit

    mesh = make_mesh(data=8, model=1)
    c_staged = cfg.replace(train=dataclasses.replace(
        cfg.train, scan_chunk=2, stage_epoch_recipes=True))
    c_stream = cfg.replace(train=dataclasses.replace(
        cfg.train, scan_chunk=2, stage_epoch_recipes=False))
    _, h_staged = fit(ds, c_staged, epochs=2, mesh=mesh)
    _, h_stream = fit(ds, c_stream, epochs=2, mesh=mesh)
    for rs, rt in zip(h_staged, h_stream):
        for k in ("train_qloss", "train_mae", "valid_mae", "test_mae"):
            assert rs[k] == rt[k], (k, rs[k], rt[k])


def test_fit_with_mesh_staged_byte_cap_falls_back(ds, cfg, caplog):
    """The sharded staging fallback (stage_recipes_max_mb exceeded ->
    per-chunk put with a length-1 replicated epoch axis, sliced away on
    device) must warn and keep the exact staged trajectory."""
    import dataclasses
    import logging

    from pertgnn_tpu.train.loop import fit

    mesh = make_mesh(data=8, model=1)
    c_staged = cfg.replace(train=dataclasses.replace(
        cfg.train, scan_chunk=2, stage_epoch_recipes=True))
    c_capped = cfg.replace(train=dataclasses.replace(
        cfg.train, scan_chunk=2, stage_epoch_recipes=True,
        stage_recipes_max_mb=1e-6))
    _, h_staged = fit(ds, c_staged, epochs=2, mesh=mesh)
    with caplog.at_level(logging.WARNING, logger="pertgnn_tpu.train.loop"):
        _, h_capped = fit(ds, c_capped, epochs=2, mesh=mesh)
    assert any("falling back to per-chunk transfers" in r.message
               for r in caplog.records)
    for rs, rc in zip(h_staged, h_capped):
        for k in ("train_qloss", "train_mae", "valid_mae", "test_mae"):
            assert rs[k] == rc[k], (k, rs[k], rc[k])


def test_fit_with_mesh_host_packed(ds, cfg):
    """The host-packed SPMD path still works when the arena budget forces
    the fallback (arena_hbm_budget_gb=0)."""
    import dataclasses

    from pertgnn_tpu.train.loop import fit

    mesh = make_mesh(data=8, model=1)
    c = cfg.replace(train=dataclasses.replace(cfg.train,
                                              arena_hbm_budget_gb=0.0))
    _, history = fit(ds, c, epochs=1, mesh=mesh)
    assert np.isfinite(history[-1]["train_qloss"])


class TestShardEdgesModel:
    """ParallelConfig.shard_edges wired into the model (VERDICT r2 #6):
    the full PertGNN with edge_shard_mesh must match the unsharded model."""

    def test_full_model_grads_match_unsharded(self, ds, cfg):
        import optax as _optax

        from pertgnn_tpu.train.loop import _loss_fn

        mesh = make_mesh(data=8, model=1)
        batch = next(ds.batches("train"))
        assert batch.senders.shape[0] % 8 == 0  # 128-rounded budget
        model_u = make_model(cfg.model, ds.num_ms, ds.num_entries,
                             ds.num_interfaces, ds.num_rpctypes)
        model_s = make_model(cfg.model, ds.num_ms, ds.num_entries,
                             ds.num_interfaces, ds.num_rpctypes,
                             edge_shard_mesh=mesh)
        tx = _optax.adam(cfg.train.lr)
        state = create_train_state(model_u, tx, batch, cfg.train.seed)
        b = jax.tree.map(jnp.asarray, batch)
        rng = jax.random.PRNGKey(0)

        def grads(model, params):
            return jax.grad(
                lambda p: _loss_fn(model, cfg, p, state.batch_stats, b,
                                   rng)[0])(params)

        # identical params work for both: edge_shard_mesh changes only how
        # the attention reduction is computed, not the parameter tree
        g_u = jax.jit(lambda p: grads(model_u, p))(state.params)
        g_s = jax.jit(lambda p: grads(model_s, p))(state.params)
        jax.tree.map(
            lambda a, c: np.testing.assert_allclose(
                np.asarray(a), np.asarray(c),
                rtol=2e-4, atol=1e-6 + 1e-4 * np.abs(np.asarray(a)).max()),
            g_u, g_s)

        out_u, _ = model_u.apply(
            {"params": state.params, "batch_stats": state.batch_stats}, b)
        out_s, _ = model_s.apply(
            {"params": state.params, "batch_stats": state.batch_stats}, b)
        np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_s),
                                   rtol=2e-4, atol=1e-5)

    def test_fit_shard_edges(self, ds, cfg):
        """fit(mesh=...) with shard_edges trains end-to-end: replicated
        batches, edge set sharded inside the layers."""
        import dataclasses

        from pertgnn_tpu.config import ParallelConfig
        from pertgnn_tpu.train.loop import fit

        mesh = make_mesh(data=8, model=1)
        c = cfg.replace(
            parallel=ParallelConfig(shard_edges=True),
            train=dataclasses.replace(cfg.train, scan_chunk=2))
        _, history = fit(ds, c, epochs=2, mesh=mesh)
        assert len(history) == 2
        assert history[1]["train_qloss"] < history[0]["train_qloss"]
        assert np.isfinite(history[-1]["test_mae"])


class TestIndexedMesh:
    """Round-2's device-materialize machinery composed with the mesh
    (VERDICT r2 #2): the SPMD program is fed sharded int32 gather recipes
    and materializes global batches from mesh-replicated arenas."""

    def test_stacked_recipe_materializes_global_batch(self, ds, cfg):
        """materialize_host(stack_index_batches(idxs)) == stack_batches of
        the per-shard batches — node/graph arrays exactly, edges as equal
        multisets (stack_batches re-sorts edges globally; the indexed path
        keeps per-shard layout, which segment attention doesn't care
        about)."""
        from pertgnn_tpu.batching.arena import materialize_host
        from pertgnn_tpu.parallel.data_parallel import stack_index_batches

        idxs = list(ds.index_batches("train"))[:4]
        batches = list(ds.batches("train"))[:4]
        want = stack_batches(batches)
        glob_idx = stack_index_batches(idxs)
        # index_batches uses the split view; its src_feat rows index the
        # FULL shared feature arena, so materialize against that
        got = materialize_host(ds.arena(), ds.feat_arena(), glob_idx)

        for f in ("x", "ms_id", "node_depth", "node_graph", "node_mask",
                  "pattern_prob", "pattern_size", "entry_id", "y",
                  "graph_mask"):
            np.testing.assert_array_equal(getattr(got, f), getattr(want, f),
                                          err_msg=f)

        def edge_key(b):
            cols = np.stack([b.edge_mask.astype(np.int64), b.receivers,
                             b.senders, b.edge_iface, b.edge_rpctype])
            return cols[:, np.lexsort(cols)]

        np.testing.assert_array_equal(edge_key(got), edge_key(want))

    def test_indexed_mesh_grads_equal_host_packed_mesh(self, ds, cfg):
        """Gradients from the indexed SPMD step == gradients from the
        host-packed SPMD step on the same global batch."""
        from pertgnn_tpu.batching.materialize import (build_device_arenas,
                                                      materialize_device)
        from pertgnn_tpu.parallel.data_parallel import (
            make_sharded_train_step, stack_index_batches)
        from pertgnn_tpu.parallel.mesh import (batch_shardings,
                                               index_batch_shardings,
                                               replicated_sharding,
                                               state_shardings)
        from pertgnn_tpu.train.loop import _loss_fn

        mesh = make_mesh(data=8, model=1)
        model, tx, state, _ = _setup(ds, cfg, mesh)
        idxs = list(ds.index_batches("train"))[:8]
        batches = list(ds.batches("train"))[:8]
        glob_pb = stack_batches(batches)
        glob_idx = stack_index_batches(idxs)
        dev = build_device_arenas(ds.arena(), ds.feat_arena(),
                                  sharding=replicated_sharding(mesh))
        st_sh = state_shardings(state, mesh)
        sh_state = jax.device_put(state, st_sh)
        rng = jax.random.PRNGKey(0)

        def grads_from_batch(state, batch):
            return jax.grad(
                lambda p: _loss_fn(model, cfg, p, state.batch_stats, batch,
                                   rng)[0])(state.params)

        i_sh = index_batch_shardings(mesh)
        g_pb = jax.jit(grads_from_batch,
                       in_shardings=(st_sh, batch_shardings(mesh)))(
            sh_state, shard_batch(glob_pb, mesh))
        g_idx = jax.jit(
            lambda s, i: grads_from_batch(s, materialize_device(dev, i)),
            in_shardings=(st_sh, i_sh))(
            sh_state, shard_batch(glob_idx, mesh, i_sh))
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b),
                rtol=1e-4, atol=1e-6 + 1e-4 * np.abs(np.asarray(a)).max()),
            jax.device_get(g_pb), jax.device_get(g_idx))

        # (full-step metric equivalence for the production path is covered
        # by test_sharded_compact_expansion_and_step)

    def test_sharded_compact_expansion_and_step(self, ds, cfg):
        """The O(graphs) SPMD path: shard-local expansion of the global
        compact recipe must equal the host-stacked IndexBatch exactly, and
        the compact SPMD train step must match the host-packed SPMD step's
        metrics on the same global batch."""
        from pertgnn_tpu.batching.materialize import (
            build_device_arenas, expand_compact_sharded)
        from pertgnn_tpu.parallel.data_parallel import (
            make_sharded_train_step, make_sharded_train_step_compact,
            stack_compact_batches, stack_index_batches)
        from pertgnn_tpu.parallel.mesh import replicated_sharding

        mesh = make_mesh(data=8, model=1)
        model, tx, state, _ = _setup(ds, cfg, mesh)
        cbs = list(ds.compact_batches("train"))[:8]
        idxs = list(ds.index_batches("train"))[:8]
        batches = list(ds.batches("train"))[:8]
        glob_cb = stack_compact_batches(cbs)
        dev = build_device_arenas(ds.arena(), ds.feat_arena(),
                                  sharding=replicated_sharding(mesh))
        mn, me = ds.budget.max_nodes, ds.budget.max_edges

        got = expand_compact_sharded(dev, jax.tree.map(jnp.asarray, glob_cb),
                                     mn, me, mesh, "data")
        want = stack_index_batches(idxs)
        for name in want._fields:
            np.testing.assert_array_equal(np.asarray(getattr(got, name)),
                                          getattr(want, name), err_msg=name)

        step_h, st_h = make_sharded_train_step(model, cfg, tx, mesh, state)
        st_h, m_h = step_h(st_h, shard_batch(stack_batches(batches), mesh))
        step_c, st_c = make_sharded_train_step_compact(
            model, cfg, tx, mesh, state, dev, mn, me)
        from pertgnn_tpu.parallel.data_parallel import (
            compact_batch_shardings)
        st_c, m_c = step_c(st_c, shard_batch(glob_cb, mesh,
                                             compact_batch_shardings(mesh)))
        np.testing.assert_allclose(float(m_h["qloss_sum"]),
                                   float(m_c["qloss_sum"]), rtol=1e-5)
        np.testing.assert_allclose(float(m_h["mae_sum"]),
                                   float(m_c["mae_sum"]), rtol=1e-5)

    def test_sharded_compact_chunked(self, ds, cfg):
        """The chunked compact SPMD path (what fit(mesh=...) runs with
        scan_chunk>1): full split coverage through grouped + chunked
        recipes with tail fillers, and single-chunk metric equality with
        the unchunked compact step."""
        from pertgnn_tpu.batching.arena import zero_masked_compact
        from pertgnn_tpu.batching.materialize import build_device_arenas
        from pertgnn_tpu.parallel.data_parallel import (
            chunk_compact_batch_shardings, compact_batch_shardings,
            grouped_compact_batches, make_sharded_train_step_compact,
            stack_compact_batches)
        from pertgnn_tpu.parallel.mesh import replicated_sharding
        from pertgnn_tpu.train.loop import _host_chunks

        mesh = make_mesh(data=8, model=1)
        model, tx, state, _ = _setup(ds, cfg, mesh)
        dev = build_device_arenas(ds.arena(), ds.feat_arena(),
                                  sharding=replicated_sharding(mesh))
        mn, me = ds.budget.max_nodes, ds.budget.max_edges
        chunk_fn, st = make_sharded_train_step_compact(
            model, cfg, tx, mesh, state, dev, mn, me, chunked=True)
        c_sh = chunk_compact_batch_shardings(mesh)
        total = 0.0
        globs = grouped_compact_batches(ds.compact_batches("train"), 8)
        for chunk in _host_chunks(globs, 3, zero_masked_compact):
            st, m = chunk_fn(st, shard_batch(chunk, mesh, c_sh))
            total += float(m["count"])
        assert total == len(ds.splits["train"])
        assert np.isfinite(float(m["qloss_sum"]))

        # single-chunk == single-step metrics (same program semantics)
        glob = stack_compact_batches(list(ds.compact_batches("train"))[:8])
        one_chunk = next(_host_chunks(iter([glob]), 1))
        chunk_fn2, st2 = make_sharded_train_step_compact(
            model, cfg, tx, mesh, state, dev, mn, me, chunked=True)
        st2, m_chunk = chunk_fn2(st2, shard_batch(one_chunk, mesh, c_sh))
        step_fn, st3 = make_sharded_train_step_compact(
            model, cfg, tx, mesh, state, dev, mn, me)
        st3, m_step = step_fn(st3, shard_batch(glob, mesh,
                                               compact_batch_shardings(mesh)))
        np.testing.assert_allclose(float(m_chunk["qloss_sum"]),
                                   float(m_step["qloss_sum"]), rtol=1e-5)
        assert int(st2.step) == int(st3.step) == 1

class TestShardedChunk:
    def test_sharded_chunk_equals_single_device_chunk(self, ds, cfg):
        """Scan-fused SPMD stepping == scan-fused single-device stepping on
        the same stacked global batches (one program either way)."""
        from pertgnn_tpu.parallel.data_parallel import (
            make_sharded_train_chunk)
        from pertgnn_tpu.parallel.mesh import make_mesh
        from pertgnn_tpu.train.loop import _host_chunks, make_train_chunk

        mesh = make_mesh(data=8, model=1)
        model, tx, state, _ = _setup(ds, cfg, mesh)
        # Strict equivalence on a SINGLE-step chunk: from step 2 on,
        # everything depends on post-Adam params, which are ill-conditioned
        # to compare (TestDataParallel docstring: near-zero gradients
        # normalize to +-lr under Adam, amplifying reduction-order noise).
        glob = stack_batches([next(ds.batches("train"))] * 8)
        chunk_batch = next(_host_chunks(iter([glob]), 1))

        sh_step, sh_state = make_sharded_train_chunk(model, cfg, tx, mesh,
                                                     state)
        sh_state, sh_m = sh_step(sh_state, jax.tree.map(jnp.asarray,
                                                        chunk_batch))

        plain_step = make_train_chunk(model, cfg, tx)
        plain_state = jax.tree.map(jnp.copy, state)
        plain_state, m = plain_step(plain_state,
                                    jax.tree.map(jnp.asarray, chunk_batch))

        np.testing.assert_allclose(float(sh_m["qloss_sum"]),
                                   float(m["qloss_sum"]), rtol=1e-5)
        np.testing.assert_allclose(float(sh_m["mae_sum"]),
                                   float(m["mae_sum"]), rtol=1e-5)
        assert int(sh_state.step) == int(plain_state.step) == 1
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
            sh_state.batch_stats, plain_state.batch_stats)

    def test_sharded_multi_step_chunk_mechanics(self, ds, cfg):
        """A 3-step sharded chunk with a zero-mask tail filler advances
        the step counter only for real batches and stays finite."""
        from pertgnn_tpu.batching.pack import zero_masked
        from pertgnn_tpu.parallel.data_parallel import (
            make_sharded_train_chunk)
        from pertgnn_tpu.parallel.mesh import make_mesh
        from pertgnn_tpu.train.loop import _host_chunks

        mesh = make_mesh(data=8, model=1)
        model, tx, state, _ = _setup(ds, cfg, mesh)
        b = next(ds.batches("train"))
        globs = [stack_batches([b] * 8), stack_batches([b] * 8),
                 zero_masked(stack_batches([b] * 8))]
        chunk_batch = next(_host_chunks(iter(globs), 3))
        sh_step, sh_state = make_sharded_train_chunk(model, cfg, tx, mesh,
                                                     state)
        sh_state, m = sh_step(sh_state, jax.tree.map(jnp.asarray,
                                                     chunk_batch))
        assert int(sh_state.step) == 2   # filler skipped
        assert np.isfinite(float(m["qloss_sum"]))
