"""graftvault (store/durable.py + store/scrub.py): the durable-write
protocol, store locks, scrubbing, and the crash-injection matrix.

The heavyweight guarantee under test: for EVERY on-disk store, a
writer SIGKILLed at any ``store.write.*`` fault site leaves the
reopened store bit-identical to its old or new state — never a third
thing — and a subsequent scrub reports CLEAN (crash residue is
orphans, not corruption). The matrix runs a REAL writer subprocess
(tests/_durable_writer.py) per case: fault plans only arm kills, the
kernel delivers them.

Bit-rot is the complementary axis: a flipped payload bit must be
detected by scrub and quarantine EXACTLY the corrupt entry, while
every healthy entry keeps warm-loading with zero rebuilds.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from pertgnn_tpu.store import durable
from pertgnn_tpu.store import scrub
from pertgnn_tpu.store.durable import (EntryWriter, StoreCorruption,
                                       StoreLock, StoreLockTimeout)
from pertgnn_tpu.testing import faults

from _durable_writer import snapshot

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_durable_writer.py")


class _Bus:
    """Minimal recording bus (duck-typed: durable.py only calls
    counter/histogram)."""

    def __init__(self):
        self.events: list[tuple[str, str, dict]] = []

    def counter(self, name, value=1, *, level=1, **tags):
        self.events.append(("counter", name, tags))

    def histogram(self, name, value, *, level=1, **tags):
        self.events.append(("histogram", name, tags))

    def count(self, name: str) -> int:
        return sum(1 for _, n, _t in self.events if n == name)


@pytest.fixture
def plan_guard():
    """Restore whatever fault plan was armed before the test."""
    prev = faults.install(None)
    yield
    faults.install(prev)


# --- CRC32C ---------------------------------------------------------------


def test_crc32c_known_answer():
    """The RFC 3720 check value — proves this is real Castagnoli, not
    zlib.crc32 wearing a trench coat."""
    assert durable.crc32c(b"123456789") == 0xE3069283


def test_crc32c_incremental_extend():
    whole = durable.crc32c(b"123456789")
    assert durable.crc32c(b"6789", durable.crc32c(b"12345")) == whole


def test_crc32c_fallback_matches_accelerated(monkeypatch):
    """The pure-python table must agree with google_crc32c byte for
    byte — a checksum written by one implementation verifies under the
    other."""
    data = bytes(range(256)) * 17 + b"tail"
    accelerated = durable.crc32c(data)
    monkeypatch.setattr(durable, "_gcrc", None)
    assert durable.crc32c(data) == accelerated
    assert durable.crc32c(b"123456789") == 0xE3069283


# --- checksummed envelope -------------------------------------------------


def test_envelope_round_trip():
    body = {"key": "abc", "n": 3, "files": {"a.npy": {"crc32c": 7}}}
    assert durable.checksummed_loads(
        durable.checksummed_dumps(body)) == body


def test_envelope_tamper_reasons():
    good = durable.checksummed_dumps({"x": 1})
    with pytest.raises(StoreCorruption) as e:
        durable.checksummed_loads(good.replace(b'"x": 1', b'"x": 2'))
    assert e.value.reason == "crc_mismatch"
    with pytest.raises(StoreCorruption) as e:
        durable.checksummed_loads(b'{"plain": "json"}')
    assert e.value.reason == "not_envelope"
    with pytest.raises(StoreCorruption) as e:
        durable.checksummed_loads(good[: len(good) // 2])
    assert e.value.reason == "undecodable"


def test_write_read_json_round_trip(tmp_path):
    path = str(tmp_path / "m.json")
    body = {"a": [1, 2], "b": "text"}
    bus = _Bus()
    durable.write_json(path, body, store="t", bus=bus)
    assert durable.read_json(path, store="t") == body
    assert bus.count("store.fsync_seconds") == 1
    # absent is the caller's cache-miss path, not corruption
    with pytest.raises(FileNotFoundError):
        durable.read_json(str(tmp_path / "gone.json"), store="t")
    # no tmp residue after a successful replace
    assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []


def test_durable_write_failure_removes_tmp(tmp_path, plan_guard):
    """An error mid-write must remove its tmp and leave the target's
    previous contents untouched."""
    path = str(tmp_path / "f.bin")
    durable.durable_write(path, b"old", store="t", bus=_Bus())
    faults.install(faults.FaultPlan([faults.FaultSpec(
        site=durable.SITE_PRE_FSYNC, kind="error")]))
    with pytest.raises(faults.InjectedFault):
        durable.durable_write(path, b"new", store="t", bus=_Bus())
    faults.install(None)
    with open(path, "rb") as f:
        assert f.read() == b"old"
    assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []


# --- store locks ----------------------------------------------------------


def test_store_lock_acquire_release_and_telemetry(tmp_path):
    lock = str(tmp_path / ".lock")
    bus = _Bus()
    with StoreLock(lock, store="t", bus=bus):
        pass
    with StoreLock(lock, store="t", bus=bus):  # released → reacquirable
        pass
    assert bus.count("store.lock_wait_ms") == 2


def test_store_lock_contention_times_out(tmp_path):
    """flock conflicts between two open file descriptions even within
    one process — the cheapest honest stand-in for a second writer."""
    lock = str(tmp_path / ".lock")
    with StoreLock(lock, store="t", bus=_Bus()):
        with pytest.raises(StoreLockTimeout, match="wedged"):
            with StoreLock(lock, store="t", timeout_s=0.05,
                           poll_s=0.005, bus=_Bus()):
                pass
    # holder released: the next writer gets through
    with StoreLock(lock, store="t", timeout_s=0.05, bus=_Bus()):
        pass


# --- EntryWriter: generation-dir commits ----------------------------------


def test_entry_writer_commit_and_resolve(tmp_path):
    root = str(tmp_path)
    with EntryWriter(root, "k1", store="t", bus=_Bus()) as w:
        w.put_bytes("blob.bin", b"payload")
        w.put_text_lines("names.txt", ["a", "b"])
        gen_dir = w.commit({"tag": "first"})
    assert os.path.basename(gen_dir) == "k1@g1"
    d, body = durable.resolve_entry(root, "k1", store="t")
    assert d == gen_dir
    assert body["meta"] == {"tag": "first"}
    assert body["files"]["blob.bin"]["crc32c"] == durable.crc32c(
        b"payload")
    # recorded per-file CRCs verify against the committed bytes
    for fn, rec in body["files"].items():
        crc, n = durable.file_crc32c(os.path.join(d, fn))
        assert (crc, n) == (rec["crc32c"], rec["bytes"]), fn


def test_entry_writer_generation_bump_gcs_old(tmp_path):
    root = str(tmp_path)
    for tag in ("first", "second"):
        with EntryWriter(root, "k1", store="t", bus=_Bus()) as w:
            w.put_bytes("blob.bin", tag.encode())
            w.commit({"tag": tag})
    d, body = durable.resolve_entry(root, "k1", store="t")
    assert body["generation"] == 2 and d.endswith("k1@g2")
    assert not os.path.exists(os.path.join(root, "k1@g1"))


def test_entry_writer_abort_on_exception_leaves_no_trace(tmp_path):
    root = str(tmp_path)
    with pytest.raises(RuntimeError, match="boom"):
        with EntryWriter(root, "k1", store="t", bus=_Bus()) as w:
            w.put_bytes("blob.bin", b"x")
            raise RuntimeError("boom")
    assert os.listdir(root) == []
    assert durable.resolve_entry(root, "k1", store="t") is None


def test_resolve_entry_corruption_reasons(tmp_path):
    root = str(tmp_path)
    durable.write_json(durable.manifest_path(root, "k1"),
                       {"key": "k1", "dir": "elsewhere"}, store="t")
    with pytest.raises(StoreCorruption) as e:
        durable.resolve_entry(root, "k1", store="t")
    assert e.value.reason == "bad_dir"
    durable.write_json(durable.manifest_path(root, "k2"),
                       {"key": "k2", "dir": "k2@g1"}, store="t")
    with pytest.raises(StoreCorruption) as e:
        durable.resolve_entry(root, "k2", store="t")
    assert e.value.reason == "missing_generation"


# --- the crash-injection matrix -------------------------------------------
# (mode, site, nth, expected surviving state). Occurrences count from
# the armed (NEW) write only — the writer re-installs a fresh plan.
# Single durable_write (sidecar) fires each site once; a gen-dir commit
# (arena/delta) or blob+manifest pair (aot) fires each site twice, the
# SECOND occurrence being the manifest — the commit point. Kills before
# the manifest rename must surface OLD; after it, NEW.

KILL_CASES = [
    ("aot", "pre_fsync", 1, "old"),       # mid blob write
    ("aot", "post_fsync", 2, "old"),      # manifest synced, not live
    ("aot", "pre_rename", 2, "old"),
    ("aot", "post_rename", 2, "new"),     # manifest live, GC skipped
    ("arena", "pre_fsync", 1, "old"),     # mid gen-dir fsync pass
    ("arena", "post_fsync", 2, "old"),
    ("arena", "pre_rename", 1, "old"),    # gen dir never renamed
    ("arena", "post_rename", 2, "new"),
    ("delta", "pre_fsync", 1, "old"),
    ("delta", "post_fsync", 2, "old"),
    ("delta", "pre_rename", 1, "old"),
    ("delta", "post_rename", 2, "new"),
    ("sidecar", "pre_fsync", 1, "old"),
    ("sidecar", "post_fsync", 1, "old"),
    ("sidecar", "pre_rename", 1, "old"),
    ("sidecar", "post_rename", 1, "new"),
    ("journal", "pre_fsync", 1, "old"),   # buffered line dies unflushed
    ("journal", "post_fsync", 1, "new"),  # the fsync IS the commit
]


def _run_child(mode: str, root: str, out: str, *,
               fault_plan: str | None = None,
               wait: bool = True):
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop(faults.ENV_VAR, None)
    if fault_plan is not None:
        env[faults.ENV_VAR] = fault_plan
    proc = subprocess.Popen(
        [sys.executable, CHILD, mode, root, out],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    if not wait:
        return proc
    stdout, stderr = proc.communicate(timeout=180)
    return proc.returncode, stdout.decode(), stderr.decode()


def _load_snap(out: str, name: str) -> dict:
    with open(os.path.join(out, name)) as f:
        return json.load(f)


def _scrub_mode(mode: str, root: str):
    kw = {"aot": {"aot_dir": root}, "arena": {"arena_dir": root},
          "delta": {"delta_dir": root}, "sidecar": {"checkpoint_dir": root},
          "journal": {"journal": os.path.join(root, "journal.jsonl")}}
    return scrub.scrub_all(bus=_Bus(), **kw[mode])


@pytest.fixture(scope="module")
def reference_run(tmp_path_factory):
    """Per-mode (old, new) snapshots from one UNARMED child run. The
    writer freezes clocks and pid, so a kill run's bytes are comparable
    hash-for-hash."""
    cache: dict[str, tuple[dict, dict]] = {}

    def get(mode: str) -> tuple[dict, dict]:
        if mode not in cache:
            base = tmp_path_factory.mktemp(f"ref_{mode}")
            root, out = str(base / "root"), str(base / "out")
            rc, _so, se = _run_child(mode, root, out)
            assert rc == 0, f"reference {mode} writer failed:\n{se}"
            cache[mode] = (_load_snap(out, "old.json"),
                           _load_snap(out, "new.json"))
        return cache[mode]

    return get


@pytest.mark.parametrize("mode,site,nth,expect", KILL_CASES)
def test_kill_matrix_old_or_new_never_a_third_thing(
        mode, site, nth, expect, reference_run, tmp_path):
    old, new = reference_run(mode)
    assert old != new, "reference run must distinguish old from new"
    root, out = str(tmp_path / "root"), str(tmp_path / "out")
    plan = faults.FaultPlan([faults.FaultSpec(
        site=f"store.write.{site}", kind="kill", nth=(nth,))])
    rc, _so, se = _run_child(mode, root, out, fault_plan=plan.to_json())
    assert rc == 137, f"writer was not killed at {site}#{nth}:\n{se}"
    survived = snapshot(root)
    assert survived in (old, new), (
        f"{mode} kill at {site}#{nth} left a THIRD state:\n"
        f"{json.dumps(survived, indent=1, sort_keys=True)}")
    assert survived == (new if expect == "new" else old)
    # crash residue is orphans, never corruption: the reopened store
    # scrubs CLEAN and stays bit-identical afterwards
    reports, code = _scrub_mode(mode, root)
    assert code == 0, reports
    assert all(not r["corrupt"] for r in reports)


def test_kill_leaves_loadable_sidecar_state(tmp_path):
    """Beyond hashes: after a pre-commit kill the sidecar actually
    LOADS as the old config (the reader-visible form of 'old')."""
    root, out = str(tmp_path / "root"), str(tmp_path / "out")
    plan = faults.FaultPlan([faults.FaultSpec(
        site=durable.SITE_PRE_RENAME, kind="kill", nth=(1,))])
    rc, _so, se = _run_child("sidecar", root, out,
                             fault_plan=plan.to_json())
    assert rc == 137, se
    body = durable.read_json(os.path.join(root, "train_config.json"),
                             store="checkpoint")
    assert body["model"]["hidden_channels"] == ord("A")


# --- concurrent writers ---------------------------------------------------


def test_concurrent_aot_writers_one_winner_no_corruption(tmp_path):
    """Two processes warm-save the same AOT entry at once: the store
    lock serializes them, exactly one generation survives, the manifest
    verifies, and the loser's subsequent warm load is bit-identical to
    what it tried to save."""
    import pickle

    root, out = str(tmp_path / "root"), str(tmp_path / "out")
    os.makedirs(out, exist_ok=True)
    procs = [_run_child("race-aot", root, out, wait=False)
             for _ in range(2)]
    with open(os.path.join(out, "go"), "w") as f:
        f.write("go")
    for p in procs:
        _stdout, stderr = p.communicate(timeout=180)
        assert p.returncode == 0, stderr.decode()
    slot = os.path.join(root, "prog")
    blobs = [f for f in os.listdir(slot) if f.endswith(".bin")]
    assert len(blobs) == 1, blobs  # exactly one winner generation
    body = durable.read_json(os.path.join(slot, "cafe01.json"),
                             store="aot")
    assert body["blob"] == blobs[0]
    with open(os.path.join(slot, blobs[0]), "rb") as f:
        data = f.read()
    assert durable.crc32c(data) == body["blob_crc32c"]
    assert len(data) == body["blob_bytes"]
    # both writers saved identical payloads — whoever lost the rename
    # race warm-loads the winner's bytes and sees exactly its own
    assert pickle.loads(data)["payload"] == b"R" * 2048
    reports, code = scrub.scrub_all(aot_dir=root, bus=_Bus())
    assert code == 0, reports


# --- bit-rot: scrub detects, quarantines EXACTLY the corrupt entry --------


def _flip_one_bit(path: str, offset: int = 100) -> None:
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0x04]))


def test_scrub_dir_store_quarantines_exactly_the_corrupt_entry(tmp_path):
    root = str(tmp_path)
    for key in ("aaaa", "bbbb"):
        with EntryWriter(root, key, store="arena", bus=_Bus()) as w:
            w.put_bytes("arena_a.bin", key.encode() * 200)
            w.commit({"key": key})
    _flip_one_bit(os.path.join(root, "aaaa@g1", "arena_a.bin"))
    reports, code = scrub.scrub_all(arena_dir=root, bus=_Bus())
    assert code == 1
    (r,) = reports
    assert [c["entry"] for c in r["corrupt"]] == ["aaaa"]
    assert r["corrupt"][0]["reason"] == "crc_mismatch"
    # exactly the corrupt entry moved aside; evidence preserved
    assert not os.path.exists(durable.manifest_path(root, "aaaa"))
    assert not os.path.exists(os.path.join(root, "aaaa@g1"))
    q = os.listdir(os.path.join(root, ".quarantine"))
    assert any(n.startswith("aaaa.manifest.json.") for n in q)
    assert any(n.startswith("aaaa@g1.") for n in q)
    # the healthy entry is untouched and still verifies
    d, body = durable.resolve_entry(root, "bbbb", store="arena")
    crc, n = durable.file_crc32c(os.path.join(d, "arena_a.bin"))
    assert crc == body["files"]["arena_a.bin"]["crc32c"]
    # second scrub: the store is clean again
    reports, code = scrub.scrub_all(arena_dir=root, bus=_Bus())
    assert code == 0 and not reports[0]["corrupt"]


def test_scrub_flipped_bit_other_entries_warm_load_zero_rebuilds(
        preprocessed, tmp_path):
    """The acceptance drill on the REAL arena store: flip one payload
    bit in one entry; scrub quarantines exactly it; the other entry
    keeps warm-loading with zero rebuilds."""
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.batching.arena_store import ArenaStore, arena_cache_key
    from pertgnn_tpu.config import (Config, DataConfig, IngestConfig,
                                    ModelConfig)

    def cfg(graph_type):
        return Config(ingest=IngestConfig(min_traces_per_entry=10),
                      data=DataConfig(max_traces=200, batch_size=16),
                      model=ModelConfig(hidden_channels=8, num_layers=1),
                      graph_type=graph_type)

    fp = {"kind": "test", "seed": 7}
    root = str(tmp_path / "arena")
    store = ArenaStore(root)
    for gt in ("pert", "span"):
        store.load_or_build(cfg(gt), fp,
                            lambda gt=gt: build_dataset(preprocessed,
                                                        cfg(gt)))
    victim_key, _ = arena_cache_key(cfg("pert"), fp)
    healthy_key, _ = arena_cache_key(cfg("span"), fp)
    _flip_one_bit(os.path.join(store._entry_dir(victim_key),
                               "arena_ms_id.npy"))
    bus = _Bus()
    reports, code = scrub.scrub_all(arena_dir=root, bus=bus)
    assert code == 1
    assert [c["entry"] for c in reports[0]["corrupt"]] == [victim_key]
    assert bus.count("store.quarantined") == 1
    assert durable.resolve_entry(root, victim_key, store="arena") is None
    # the healthy entry warm-loads — build_fn is unreachable

    from pertgnn_tpu import telemetry

    class _ArenaBus(telemetry.NoopBus):  # full bus surface for the store
        def __init__(self):
            self.events = []

        def counter(self, name, value=1, *, level=1, **tags):
            self.events.append(("counter", name, tags))

        def count(self, name):
            return sum(1 for _, n, _t in self.events if n == name)

    warm_bus = _ArenaBus()
    ds = ArenaStore(root, bus=warm_bus).load_or_build(
        cfg("span"), fp, lambda: pytest.fail(
            "healthy entry must warm-load with zero rebuilds"))
    assert warm_bus.count("arena.cache_hit") == 1
    assert warm_bus.count("arena.cache_miss") == 0
    assert len(ds.splits["train"]) > 0
    assert durable.resolve_entry(root, healthy_key,
                                 store="arena") is not None


def test_scrub_aot_quarantines_exactly_the_corrupt_blob(tmp_path):
    """AOT layout built from the same primitives _save uses: a flipped
    blob bit is caught by the manifest CRC before any unpickle."""
    root = str(tmp_path)
    slot = os.path.join(root, "prog")
    for key, payload in (("aaaa", b"A" * 4096), ("bbbb", b"B" * 4096)):
        blob = f"{key}@g1.bin"
        durable.durable_write(os.path.join(slot, blob), payload,
                              store="aot", bus=_Bus())
        durable.write_json(
            os.path.join(slot, f"{key}.json"),
            {"key": key, "format": "stablehlo", "blob": blob,
             "blob_crc32c": durable.crc32c(payload),
             "blob_bytes": len(payload)}, store="aot", bus=_Bus())
    _flip_one_bit(os.path.join(slot, "aaaa@g1.bin"), offset=2048)
    reports, code = scrub.scrub_all(aot_dir=root, bus=_Bus())
    assert code == 1
    (r,) = reports
    assert [c["entry"] for c in r["corrupt"]] == ["prog/aaaa"]
    assert r["corrupt"][0]["reason"] == "crc_mismatch"
    assert not os.path.exists(os.path.join(slot, "aaaa.json"))
    assert os.path.exists(os.path.join(slot, "bbbb.json"))
    # healthy blob still verifies; rescrub is clean
    body = durable.read_json(os.path.join(slot, "bbbb.json"),
                             store="aot")
    crc, n = durable.file_crc32c(os.path.join(slot, body["blob"]))
    assert (crc, n) == (body["blob_crc32c"], body["blob_bytes"])
    reports, code = scrub.scrub_all(aot_dir=root, bus=_Bus())
    assert code == 0


def test_scrub_sweeps_orphan_generations_as_clean(tmp_path):
    """A crashed writer's unreferenced generation and stale tmp dir are
    residue: swept, counted, CLEAN — never 'corruption'."""
    root = str(tmp_path)
    with EntryWriter(root, "k1", store="arena", bus=_Bus()) as w:
        w.put_bytes("a.bin", b"live")
        w.commit({"key": "k1"})
    os.makedirs(os.path.join(root, "k1@g7"))  # unreferenced generation
    os.makedirs(os.path.join(root, ".tmp.k1.999"))
    bus = _Bus()
    reports, code = scrub.scrub_all(arena_dir=root, bus=bus)
    assert code == 0
    assert reports[0]["orphans_removed"] == 2
    assert bus.count("store.scrub.orphans") == 1
    assert not os.path.exists(os.path.join(root, "k1@g7"))
    assert durable.resolve_entry(root, "k1", store="arena") is not None


# --- journal record CRCs --------------------------------------------------


def test_journal_interior_bit_rot_skipped_loudly(tmp_path, caplog):
    from pertgnn_tpu.telemetry.capture import CaptureJournal

    path = str(tmp_path / "journal.jsonl")
    j = CaptureJournal(path)
    for step in (1, 2, 3):
        j.stage("probe", "done", step=step)
    with open(path) as f:
        lines = f.read().splitlines()
    # tamper a FIELD VALUE of the middle record: still valid JSON and
    # schema, only the record CRC can catch it
    assert '"step": 2' in lines[1]
    lines[1] = lines[1].replace('"step": 2', '"step": 20')
    with open(path, "w") as f:  # graftlint: allow-durable-write
        f.write("\n".join(lines) + "\n")
    import logging
    logging.getLogger("pertgnn_tpu").propagate = True
    with caplog.at_level(logging.WARNING, logger="pertgnn_tpu"):
        recs = CaptureJournal(path).records()
    assert [r["fields"]["step"] for r in recs] == [1, 3]
    assert any("crc mismatch" in r.message for r in caplog.records)
    report = scrub.scrub_journal(path)
    assert [c["entry"] for c in report["corrupt"]] == ["line 2"]


def test_journal_torn_tail_is_clean_crash_residue(tmp_path):
    from pertgnn_tpu.telemetry.capture import CaptureJournal

    path = str(tmp_path / "journal.jsonl")
    j = CaptureJournal(path)
    j.stage("probe", "done", step=1)
    with open(path, "ab") as f:  # graftlint: allow-durable-write
        f.write(b'{"v": 2, "t": 1.0, "torn half of a rec')
    assert len(CaptureJournal(path).records()) == 1
    report = scrub.scrub_journal(path)
    assert report["torn_tail"] == 1 and not report["corrupt"]
    reports, code = scrub.scrub_all(journal=path, bus=_Bus())
    assert code == 0


# --- scrub CLI ------------------------------------------------------------


def test_scrub_cli_exit_codes_and_report(tmp_path, capsys):
    root = str(tmp_path)
    with EntryWriter(root, "k1", store="arena", bus=_Bus()) as w:
        w.put_bytes("a.bin", b"payload" * 100)
        w.commit({"key": "k1"})
    assert scrub.main(["--arena_dir", root]) == 0
    assert "CLEAN" in capsys.readouterr().out
    _flip_one_bit(os.path.join(root, "k1@g1", "a.bin"))
    assert scrub.main(["--arena_dir", root, "--dry_run"]) == 1
    out = capsys.readouterr().out
    assert "would quarantine" in out and "CORRUPTION FOUND" in out
    # dry run touched nothing
    assert os.path.exists(durable.manifest_path(root, "k1"))
    assert scrub.main(["--arena_dir", root, "--json"]) == 1
    assert json.loads(capsys.readouterr().out)["clean"] is False
    with pytest.raises(SystemExit):  # nothing to scrub = usage error
        scrub.main([])
