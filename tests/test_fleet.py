"""The serve fleet (pertgnn_tpu/fleet/ — ISSUE 7).

Three layers, cheapest first:

1. the DISPATCH POLICY as pure functions — least-loaded choice,
   deadline-infeasible shed, membership add/remove/flap, and
   requeue-on-worker-loss ordering, with no subprocesses, sockets, or
   clocks (the unit-testability the policy module exists for);
2. the serve/queue TRANSPORT SEAM — ``requeue()`` hands unstarted work
   back with futures unresolved, and the probe body carries queue
   depth / in-flight / per-class error counts;
3. ONE in-process fleet — a real FleetRouter over real WorkerServer
   HTTP transports (sharing one engine, so the test pays one warmup)
   including a worker-loss drill, plus the tier-1 wiring of
   ``benchmarks/fleet_bench.py --smoke`` (a real multi-process fleet
   with a SIGKILL chaos pass — the exit code IS the assertion).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from pertgnn_tpu.batching import build_dataset
from pertgnn_tpu.config import (Config, DataConfig, FleetConfig,
                                IngestConfig, ModelConfig, ServeConfig,
                                TrainConfig)
from pertgnn_tpu.fleet import policy
from pertgnn_tpu.fleet.policy import WorkerView
from pertgnn_tpu.serve.errors import (DeadlineExceeded, QueueClosed,
                                      QueueFull, Shed)
from pertgnn_tpu.serve.queue import MicrobatchQueue

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- 1. pure policy ------------------------------------------------------

class TestChooseWorker:
    def test_least_loaded_wins(self):
        ws = [WorkerView("a", inflight_batches=2, ewma_batch_s=0.01),
              WorkerView("b", inflight_batches=0, ewma_batch_s=0.01),
              WorkerView("c", inflight_batches=1, ewma_batch_s=0.01)]
        assert policy.choose_worker(ws).worker_id == "b"

    def test_latency_weighs_against_depth(self):
        # a has the shorter queue but is 10x slower per batch: the
        # earliest PREDICTED COMPLETION is b's, depth notwithstanding
        ws = [WorkerView("a", inflight_batches=0, ewma_batch_s=0.5),
              WorkerView("b", inflight_batches=1, ewma_batch_s=0.05)]
        assert policy.choose_worker(ws).worker_id == "b"

    def test_unhealthy_excluded(self):
        ws = [WorkerView("a", healthy=False),
              WorkerView("b", inflight_batches=1)]
        assert policy.choose_worker(ws).worker_id == "b"

    def test_saturated_excluded_and_none_when_all_full(self):
        ws = [WorkerView("a", inflight_batches=2, slots=2),
              WorkerView("b", inflight_batches=2, slots=2)]
        assert policy.choose_worker(ws) is None
        ws[1] = WorkerView("b", inflight_batches=1, slots=2)
        assert policy.choose_worker(ws).worker_id == "b"

    def test_no_healthy_workers_is_none(self):
        assert policy.choose_worker(
            [WorkerView("a", healthy=False)]) is None
        assert policy.choose_worker([]) is None

    def test_deterministic_tie_break(self):
        ws = [WorkerView("b"), WorkerView("a")]
        assert policy.choose_worker(ws).worker_id == "a"
        assert policy.choose_worker(list(reversed(ws))).worker_id == "a"


class TestDeadlineFeasibility:
    def test_feasible_when_a_worker_can_make_it(self):
        ws = [WorkerView("a", inflight_batches=4, ewma_batch_s=1.0),
              WorkerView("b", inflight_batches=0, ewma_batch_s=0.01)]
        assert not policy.deadline_infeasible(ws, now=100.0,
                                              deadline_abs=100.1)

    def test_infeasible_when_every_worker_is_too_deep(self):
        ws = [WorkerView("a", inflight_batches=4, ewma_batch_s=1.0),
              WorkerView("b", inflight_batches=3, ewma_batch_s=1.0)]
        assert policy.deadline_infeasible(ws, now=100.0,
                                          deadline_abs=100.5)

    def test_saturated_workers_still_count_as_capacity(self):
        # at slot capacity but fast: the request can wait for a slot
        # and still meet its deadline — not a door shed
        ws = [WorkerView("a", inflight_batches=2, slots=2,
                         ewma_batch_s=0.001)]
        assert not policy.deadline_infeasible(ws, now=0.0,
                                              deadline_abs=1.0)

    def test_empty_membership_is_infeasible(self):
        assert policy.deadline_infeasible(
            [WorkerView("a", healthy=False)], now=0.0, deadline_abs=1e9)


class _R:
    def __init__(self, seq):
        self.seq = seq

    def __repr__(self):
        return f"R{self.seq}"


class TestRequeueOrdering:
    def test_recovered_work_goes_in_front_in_submission_order(self):
        pending = [_R(7), _R(8)]
        lost = [_R(4), _R(2)]  # one lost batch, arbitrary order
        merged = policy.merge_requeue(pending, lost)
        assert [r.seq for r in merged] == [2, 4, 7, 8]

    def test_two_losses_interleave_by_submission_seq(self):
        # worker A lost batch [1, 3]; its requeue lands, then worker B
        # loses [2, 5]: the final order is the GLOBAL submission order
        # — the later-recovered-but-older batch does not get cut in
        # line by the younger one
        pending = [_R(9)]
        after_a = policy.merge_requeue(pending, [_R(3), _R(1)])
        assert [r.seq for r in after_a] == [1, 3, 9]
        after_b = policy.merge_requeue(after_a, [_R(5), _R(2)])
        assert [r.seq for r in after_b] == [1, 2, 3, 5, 9]

    def test_empty_cases(self):
        assert policy.merge_requeue([], []) == []
        p = [_R(1)]
        assert [r.seq for r in policy.merge_requeue(p, [])] == [1]
        assert [r.seq for r in policy.merge_requeue([], p)] == [1]

    def test_pure_inputs_untouched(self):
        pending, lost = [_R(5)], [_R(1)]
        out = policy.merge_requeue(pending, lost)
        assert len(pending) == 1 and len(lost) == 1 and len(out) == 2


class TestMembership:
    def test_one_dropped_probe_does_not_flap(self):
        healthy, fails, event = policy.probe_transition(
            True, 0, probe_ok=False, lost_after=2)
        assert healthy and fails == 1 and event is None

    def test_consecutive_failures_exclude(self):
        healthy, fails, event = policy.probe_transition(
            True, 1, probe_ok=False, lost_after=2)
        assert not healthy and fails == 2 and event == "lost"

    def test_success_resets_the_streak(self):
        healthy, fails, event = policy.probe_transition(
            True, 1, probe_ok=True, lost_after=2)
        assert healthy and fails == 0 and event is None

    def test_readmitted_on_first_success(self):
        healthy, fails, event = policy.probe_transition(
            False, 5, probe_ok=True, lost_after=2)
        assert healthy and fails == 0 and event == "recovered"

    def test_excluded_member_stays_excluded_on_failure(self):
        healthy, fails, event = policy.probe_transition(
            False, 3, probe_ok=False, lost_after=2)
        assert not healthy and event is None

    def test_full_flap_cycle(self):
        state = (True, 0)
        events = []
        for ok in (False, False, True, False, False):
            h, f, ev = policy.probe_transition(*state, ok, lost_after=2)
            state = (h, f)
            events.append(ev)
        assert events == [None, "lost", "recovered", None, "lost"]


# -- 2. the serve/queue transport seam -----------------------------------

@pytest.fixture(scope="module")
def served(preprocessed):
    from pertgnn_tpu.serve.engine import InferenceEngine
    from pertgnn_tpu.train.loop import restore_target_state

    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=10),
        data=DataConfig(max_traces=200, batch_size=16),
        model=ModelConfig(hidden_channels=8, num_layers=1),
        train=TrainConfig(label_scale=1000.0),
        serve=ServeConfig(bucket_growth=2.0, min_bucket_nodes=256,
                          min_bucket_edges=256, max_graphs_per_batch=8),
        graph_type="pert",
    )
    ds = build_dataset(preprocessed, cfg)
    _model, state = restore_target_state(ds, cfg)
    engine = InferenceEngine.from_dataset(ds, cfg, state).warmup()
    return ds, cfg, state, engine


def test_requeue_hands_back_unstarted_work_unresolved(served):
    ds, _cfg, _state, engine = served
    s = ds.splits["test"]
    # a flush deadline far in the future: submissions stay PENDING
    with MicrobatchQueue(engine, flush_deadline_ms=60_000) as q:
        futs = [q.submit(int(s.entry_ids[i]), int(s.ts_buckets[i]))
                for i in range(3)]
        handed = q.requeue()
        assert len(handed) == 3
        assert [e for e, _t, _f in handed] == \
            [int(s.entry_ids[i]) for i in range(3)]
        # futures are UNRESOLVED — the caller owns them now
        assert all(not f.done() for _e, _t, f in handed)
        assert q.probe_dict()["depth"] == 0
        # the caller can settle them (a router re-dispatches; here we
        # fail them the way a draining fleet worker does)
        for _e, _t, f in handed:
            f.set_exception(QueueClosed("requeued elsewhere"))
        assert all(f.done() for f in futs)
    # close() after requeue finds nothing pending — no hang, no error


def test_requeue_empty_queue_is_empty(served):
    _ds, _cfg, _state, engine = served
    with MicrobatchQueue(engine) as q:
        assert q.requeue() == []


def test_probe_dict_counts_errors_and_depth(served):
    ds, _cfg, _state, engine = served
    s = ds.splits["test"]
    eid, tsb = int(s.entry_ids[0]), int(s.ts_buckets[0])
    with MicrobatchQueue(engine, flush_deadline_ms=60_000,
                         max_pending=1) as q:
        fut = q.submit(eid, tsb)
        probe = q.probe_dict()
        assert probe["depth"] == 1 and probe["inflight"] == 0
        with pytest.raises(QueueFull):
            q.submit(eid, tsb)
        # the shed is a Shed (QueueFull subclass) since SLO-class
        # admission — the raise contract above is unchanged
        assert q.probe_dict()["errors"].get("Shed") == 1
        handed = q.requeue()
        handed[0][2].set_exception(QueueClosed("test cleanup"))
        assert fut.done()


def test_probe_payload_shape(served):
    ds, _cfg, _state, engine = served
    from pertgnn_tpu.serve.health import probe_payload

    with MicrobatchQueue(engine) as q:
        ready, body = probe_payload(engine, q, extra={"worker_id": "w9"})
        assert ready is True
        # the PR-4 contract fields survive unchanged...
        assert body["healthy"] is True and body["ready"] is True
        assert body["draining"] is False
        # ...and the PR-7 load extension is present
        assert set(body["queue"]) == {"depth", "inflight", "errors"}
        assert body["worker_id"] == "w9"
        q.begin_drain()
        ready2, body2 = probe_payload(engine, q)
        assert ready2 is False and body2["draining"] is True


def test_queue_stats_include_error_classes(served):
    ds, _cfg, _state, engine = served
    s = ds.splits["test"]
    with MicrobatchQueue(engine, flush_deadline_ms=5,
                         request_deadline_ms=0.01) as q:
        with pytest.raises(DeadlineExceeded):
            q.predict(int(s.entry_ids[0]), int(s.ts_buckets[0]),
                      timeout=30)
    stats = q.stats_dict()
    assert stats["errors"].get("DeadlineExceeded", 0) >= 1
    assert stats["inflight"] == 0


def test_queue_sheds_lowest_class_first(served):
    """ISSUE-13: at a full pending set the queue evicts the NEWEST
    lowest-class request for a higher-class arrival (its future
    resolves with Shed — never lost), and rejects arrivals that
    outrank nothing (fleet/shield.py drives both front doors)."""
    ds, _cfg, _state, engine = served
    s = ds.splits["test"]
    eid, tsb = int(s.entry_ids[0]), int(s.ts_buckets[0])
    with MicrobatchQueue(engine, flush_deadline_ms=60_000,
                         max_pending=2) as q:
        f_std = q.submit(eid, tsb)
        f_be = q.submit(eid, tsb, slo="best_effort")
        f_crit = q.submit(eid, tsb, slo="critical")
        exc = f_be.exception(timeout=5)
        assert isinstance(exc, Shed) and exc.slo == "best_effort"
        with pytest.raises(Shed) as shed:
            q.submit(eid, tsb, slo="best_effort")
        assert shed.value.slo == "best_effort"
        assert q.stats_dict()["pending"] == 2
        assert q.stats_dict()["shed"] == 2
        with pytest.raises(ValueError, match="unknown SLO class"):
            q.submit(eid, tsb, slo="platinum")
        handed = q.requeue()
        for _e, _t, fut in handed:
            fut.set_exception(QueueClosed("test cleanup"))
    assert f_std.done() and f_crit.done()


def test_queue_downgrade_rides_the_cheapest_rung(served):
    """ISSUE-13 brownout: a downgraded request packs through ladder
    rung 0 (engine.pack_microbatch max_rung) with BIT-IDENTICAL
    predictions (padding invariance), and batches never mix downgrade
    states — a dg pair and a normal request drain as separate engine
    batches."""
    ds, _cfg, _state, engine = served
    s = ds.splits["test"]
    rung0 = engine.ladder[0]
    # pick an entry that fits the cheapest rung solo
    pick = None
    for i in range(len(s.entry_ids)):
        dn, de = engine.request_size(int(s.entry_ids[i]))
        if dn <= rung0.max_nodes and de <= rung0.max_edges:
            pick = i
            break
    assert pick is not None, "no mixture fits the smallest rung"
    eid, tsb = int(s.entry_ids[pick]), int(s.ts_buckets[pick])
    ref = float(engine.predict_microbatch([eid], [tsb])[0])
    # engine level: the cap selects rung 0, same bits
    packed = engine.pack_microbatch([eid], [tsb], max_rung=0)
    assert packed.idx == 0
    assert float(engine.predict_microbatch([eid], [tsb],
                                           max_rung=0)[0]) == ref
    # queue level: dg-homogeneous batching (downgraded pair + one
    # normal request = two engine batches, never one mixed)
    dn, de = engine.request_size(eid)
    pair_fits = 2 * dn <= rung0.max_nodes and 2 * de <= rung0.max_edges
    b0 = engine.batches
    with MicrobatchQueue(engine, flush_deadline_ms=60_000) as q:
        futs = [q.submit(eid, tsb, slo="best_effort", downgrade=True),
                q.submit(eid, tsb, slo="best_effort", downgrade=True),
                q.submit(eid, tsb)]
        # close() drains them
    for f in futs:
        assert float(f.result(timeout=60)) == ref
    assert engine.batches - b0 == (2 if pair_fits else 3)


# -- 3. one in-process fleet (real router, real HTTP transport) ----------

def test_router_over_worker_servers_end_to_end(served):
    import threading

    from pertgnn_tpu.fleet.router import FleetRouter
    from pertgnn_tpu.fleet.transport import WorkerServer, get_probe
    from pertgnn_tpu.serve.buckets import make_bucket_ladder

    ds, cfg, _state, engine = served
    s = ds.splits["test"]
    n = min(32, len(s.entry_ids))
    ent = np.asarray(s.entry_ids[:n])
    tsb = np.asarray(s.ts_buckets[:n])
    ref = np.concatenate([engine.predict_microbatch(ent[i:i + 1],
                                                    tsb[i:i + 1])
                          for i in range(n)])

    # two HTTP fronts over ONE engine+queue (one warmup): the router
    # sees two members; padding invariance keeps answers bit-identical
    # regardless of which front a batch rides through
    q = MicrobatchQueue(engine)
    w1, w2 = WorkerServer(engine, q), WorkerServer(engine, q)
    top = make_bucket_ladder(ds.budget, cfg.serve)[-1]

    def size(eid):
        m = ds.mixtures[int(eid)]
        return m.num_nodes, m.num_edges

    try:
        status, body = get_probe(f"http://127.0.0.1:{w1.port}", 2.0)
        assert status == 200 and body["ready"]
        fcfg = FleetConfig(health_poll_interval_s=0.2,
                           dispatch_timeout_s=30.0)
        with FleetRouter(
                {"w1": f"http://127.0.0.1:{w1.port}",
                 "w2": f"http://127.0.0.1:{w2.port}"},
                size, (top.max_graphs, top.max_nodes, top.max_edges),
                cfg=fcfg) as router:
            preds = np.full(n, np.nan, np.float32)
            lost = threading.Event()

            def client(idx):
                for i in idx:
                    if i >= n // 2 and not lost.is_set():
                        lost.set()
                        w2.close()  # mid-stream worker loss
                    preds[i] = router.predict(int(ent[i]), int(tsb[i]))

            threads = [threading.Thread(target=client,
                                        args=(range(t, n, 4),))
                       for t in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            stats = router.stats_dict()
        # zero lost futures, bit-identical, dispatch spread + loss seen
        assert not np.isnan(preds).any()
        np.testing.assert_array_equal(preds, np.asarray(ref, np.float32))
        assert stats["served"] == n and stats["failed"] == 0
        assert stats["dispatched_requests"] >= n
        assert stats["worker_lost"] >= 1 or stats["workers"]["w2"][
            "dispatches"] == 0  # loss may precede any w2 dispatch
    finally:
        w1.close()
        q.close()


def test_router_door_shed_when_infeasible(served):
    from pertgnn_tpu.fleet.router import FleetRouter
    from pertgnn_tpu.fleet.transport import WorkerServer
    from pertgnn_tpu.serve.buckets import make_bucket_ladder

    ds, cfg, _state, engine = served
    s = ds.splits["test"]
    q = MicrobatchQueue(engine)
    w = WorkerServer(engine, q)
    top = make_bucket_ladder(ds.budget, cfg.serve)[-1]

    def size(eid):
        m = ds.mixtures[int(eid)]
        return m.num_nodes, m.num_edges

    try:
        # a deadline no predicted completion can meet (the policy floor
        # is DEFAULT_BATCH_S=50ms against a 1ms deadline): shed AT THE
        # DOOR, before the request occupies a pending slot
        fcfg = FleetConfig(request_deadline_ms=1e-3)
        with FleetRouter({"w": f"http://127.0.0.1:{w.port}"}, size,
                         (top.max_graphs, top.max_nodes, top.max_edges),
                         cfg=fcfg) as router:
            with pytest.raises(DeadlineExceeded, match="door"):
                router.submit(int(s.entry_ids[0]), int(s.ts_buckets[0]))
            assert router.stats_dict()["shed_infeasible"] == 1
            assert router.stats_dict()["pending"] == 0
    finally:
        w.close()
        q.close()


def test_fleet_bench_smoke():
    """The tier-1 wiring (ISSUE 7 satellite): a REAL two-worker fleet —
    spawn warm from shared caches, route traffic, SIGKILL one worker
    mid-stream — exit-code-asserted by benchmarks/fleet_bench.py
    --smoke. Keeps the fleet path from silently rotting."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "fleet_bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=540,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (
        f"fleet_bench --smoke failed (rc={proc.returncode})\n"
        f"stdout: {proc.stdout[-2000:]}\nstderr: {proc.stderr[-2000:]}")
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["value"] == 1 and verdict["violations"] == []
    assert verdict["results"]["chaos"]["served"] == \
        verdict["results"]["chaos"]["requests"]
    # "warm in seconds": generous CI bound, tight enough to catch a
    # fleet that silently re-ingests or recompiles (minutes)
    assert time.monotonic() - t0 < 300
