"""Benchmark: END-TO-END training throughput of the flagship model.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "graphs/s", "vs_baseline": N, ...}

What is measured (VERDICT r2 #1):
- **value** — the headline — is the MEDIAN over >=5 real `fit()` training
  epochs: fresh shuffled data each epoch, vectorized index packing in a
  background thread, chip-resident arenas, device-side batch
  materialization, scan-fused steps. Nothing is pre-staged; this is the
  throughput a user's training run sees (epoch 0 is dropped: compile).
- **ceiling_graphs_per_s** is the cached-chunk replay ceiling (the same
  jitted program re-fed one device-resident chunk — pure device compute +
  dispatch, zero input pipeline). Ceiling windows are INTERLEAVED between
  fit epochs so the known tunnel/clock variance (ops/pallas_attention.py
  notes +-40% on microbenches) hits both numbers alike; both carry their
  window lists and spread.
- **fit_over_ceiling** quantifies everything between real training and
  the pure-compute ceiling. It decomposes via a second interleaved
  ceiling, **compact_ceiling_graphs_per_s** (the production compact
  program — on-device recipe expansion + arena materialization — replayed
  on one resident recipe chunk): `fit_over_compact_ceiling` is the input
  pipeline alone (host packing + recipe transfer — what the arena
  machinery exists to remove), `compact_over_packed` is the on-device
  expansion cost.
- **mfu_pct** relates graphs/s to chip peak via XLA cost analysis
  (utils/flops.py).

Cold start (ISSUE 3): every compile persists to the on-disk cache at
$PERTGNN_COMPILE_CACHE_DIR (default benchmarks/compile_cache), and
`bench.py --precompile` populates it ahead of a capture window — run by
tpu_watch.sh the moment the tunnel answers, so the measured window's
first step is execute-only. The result JSON's `compile_cache` field
reports the hit/miss split as evidence.

The baseline is MEASURED here, not looked up (the reference publishes no
numbers — BASELINE.md): a faithful torch-CPU re-implementation of the
reference's training step (PyG TransformerConv semantics via torch scatter
ops, BatchNorm1d, Adam, pinball loss) runs on the same packed batches on
this host. vs_baseline = our fit() graphs/s / torch's graphs/s.

Configuration mirrors the reference defaults (hidden 32, batch 170, pert
graphs; pert_gnn.py:15-33) on a synthetic workload sized so one epoch is
long enough to time reliably.
"""

from __future__ import annotations

import json
import os
import statistics
import time

import numpy as np

# Scale knobs for smoke-testing the bench itself off-TPU (the driver runs
# the defaults on the real chip). The default sizes one training epoch to
# ~100 ms of TPU device time so the fit measurement is not dominated by
# per-epoch fixed costs; on a CPU backend (or wedged-tunnel fallback) the
# workload auto-shrinks so the bench still completes in minutes.
_TRACES_PER_ENTRY = int(os.environ.get("BENCH_TRACES_PER_ENTRY", "12500"))
_CPU_TRACES_PER_ENTRY = 300
_WINDOWS = int(os.environ.get("BENCH_WINDOWS", "6"))

# Wedge-resilient capture (round 5): the axon relay flaps on minute
# timescales, and a flap mid-bench used to lose EVERY already-measured
# window when the watcher's outer `timeout` killed the process (a blocked
# PJRT call never raises, so in-process guards can't fire). Every
# completed window/phase is therefore flushed to this partial file the
# moment it exists; `bench.py --finalize-partial` (host-only, run by the
# watcher after a dead bench) promotes >=_MIN_FIT_WINDOWS fit windows
# into the pinned official result.
_PARTIAL = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "bench_partial_tpu.json")
# a promotable salvage displaced by a NEW bench attempt parks here so the
# new attempt dying early can't destroy it (finalizer falls back to it)
_ORPHAN = _PARTIAL + ".orphan"
_PIN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "benchmarks", "last_good_tpu.json")
_MIN_FIT_WINDOWS = 3

# Persistent compile cache (ISSUE 3): executables land on disk keyed by
# (HLO, backend) so a bench attempt never re-pays a compile an earlier
# attempt (or the host-side `bench.py --precompile` stage the watcher
# runs before arming a window) already performed — first-step wall time
# inside a scarce TPU window becomes execute-only. Empty env disables.
_CACHE_DIR = os.environ.get(
    "PERTGNN_COMPILE_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "benchmarks", "compile_cache"))

# Persistent arena store (ISSUE 5): the synthetic workload's dataset
# arenas persist across bench attempts keyed on the generator spec, so
# a warm attempt skips the ingest+graph+featurize rebuild (minutes at
# the TPU-sized corpus) the same way the compile cache skips XLA. Empty
# env disables.
_ARENA_DIR = os.environ.get(
    "PERTGNN_ARENA_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "benchmarks", "arena_cache"))

# Backend-probe verdict cache (ISSUE 5 satellite): BENCH_r05 burned
# 4x75 s re-timing-out IDENTICAL dead-relay probes before every
# fallback run of the round; the verdict now persists for
# BENCH_PROBE_CACHE_TTL_S (default 1 h). `bench.py --reprobe` forces a
# fresh probe.
_PROBE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "backend_probe.json")

# graftprobe capture journal (ISSUE 17): the append-only stage journal
# `bench.py --capture` re-enters (telemetry/capture.py holds the state
# machine), benchmarks/adjudicate.py --stitch assembles a measurement
# from, and tpu_watch.sh journals its probe attempts into. Fixed path:
# re-entry across processes must find the same file.
_JOURNAL = os.environ.get(
    "BENCH_CAPTURE_JOURNAL",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "benchmarks", "capture_journal.jsonl"))
# bounded per-window jax.profiler traces land under here (first
# _PROFILE_MAX_WINDOWS fit windows only; off by default on CPU —
# BENCH_CAPTURE_PROFILE=1 forces on, =0 forces off)
_PROFILE_DIR = os.environ.get(
    "BENCH_CAPTURE_PROFILE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "benchmarks", "capture_profile"))
_PROFILE_MAX_WINDOWS = 2


def _update_partial(**fields) -> None:
    """Merge fields into the partial-capture file (atomic rename so a kill
    mid-write can't corrupt it). Cost is ~ms against >=0.4 s windows."""
    data = {}
    try:
        with open(_PARTIAL) as f:
            data = json.load(f)
    except (OSError, ValueError):
        pass  # absent/corrupt partial: start a fresh one
    data.update(fields)
    data["updated_unix_time"] = time.time()
    tmp = _PARTIAL + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1)
    os.replace(tmp, _PARTIAL)


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _n_fit_windows(d: dict | None) -> int:
    return len((d or {}).get("fit_windows") or [])


def _salvage_rank(d: dict | None) -> tuple[bool, int]:
    """Orders salvage candidates: any on-chip capture outranks any
    CPU-fallback one (only TPU results can be pinned), then more fit
    windows wins."""
    return ((d or {}).get("backend") == "tpu", _n_fit_windows(d))


def _discard_partials(keep_tpu_salvage: bool = False) -> None:
    """Remove salvage files. With keep_tpu_salvage (a completed run that
    did NOT pin an on-chip result), a still-promotable TPU salvage
    survives for a later finalize — a CPU fallback must never destroy the
    round's only chip windows."""
    for path in (_PARTIAL, _ORPHAN):
        if keep_tpu_salvage:
            d = _read_json(path)
            if (d and d.get("backend") == "tpu"
                    and _n_fit_windows(d) >= _MIN_FIT_WINDOWS):
                continue
        try:
            os.remove(path)
        except OSError:
            pass


def build_workload(traces_per_entry: int = _TRACES_PER_ENTRY):
    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import (CompileCacheConfig, Config, DataConfig,
                                    IngestConfig, ModelConfig, TrainConfig)
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess

    spec = synthetic.SyntheticSpec(
        num_microservices=60, num_entries=16, patterns_per_entry=4,
        traces_per_entry=traces_per_entry, seed=42)
    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=1_000_000, batch_size=170,
                        arena_cache_dir=_ARENA_DIR),
        # the fused kernel runs compiled only on TPU; off-TPU it would
        # fall to (very slow) interpret mode. Keep the default segment
        # path either way: bench measures the flagship configuration.
        # BENCH_ATTENTION_IMPL selects a kernel variant for capture A/Bs
        # (segment | pallas | pallas_fused | blocked_dense); the result
        # JSON stamps whichever ran (attention_impl + roofline fields).
        model=ModelConfig(hidden_channels=32, num_layers=3,
                          attention_impl=os.environ.get(
                              "BENCH_ATTENTION_IMPL", "segment")),
        train=TrainConfig(lr=3e-4, label_scale=1000.0, scan_chunk=16),
        aot=CompileCacheConfig(cache_dir=_CACHE_DIR),
        graph_type="pert",
    )

    def build():
        data = synthetic.generate(spec)
        pre = preprocess(data.spans, data.resources, cfg.ingest)
        return build_dataset(pre, cfg)

    if not _ARENA_DIR:
        return build(), cfg
    # warm attempts (and the --precompile stage before a capture
    # window) reconstruct the dataset from the mmap'd arena store
    # instead of re-running ingest+graph+featurize
    from pertgnn_tpu.batching.arena_store import ArenaStore

    import dataclasses as _dc

    ds = ArenaStore(_ARENA_DIR).load_or_build(
        cfg, {"kind": "synthetic-bench", **_dc.asdict(spec)}, build)
    return ds, cfg


def _window_runner(chunk, state, chunk_batch, graphs_per_chunk):
    """Time repeated replays of one device-resident chunk. Sizes a window
    to ~0.4 s so it rides out dispatch jitter."""
    import jax

    state, m = chunk(state, chunk_batch)  # compile + warm
    jax.block_until_ready(m["qloss_sum"])
    t0 = time.perf_counter()
    state, m = chunk(state, chunk_batch)
    jax.block_until_ready(m["qloss_sum"])
    per_chunk = max(time.perf_counter() - t0, 1e-5)
    reps = max(3, int(0.4 / per_chunk))
    holder = {"state": state}

    def run_window() -> float:
        s = holder["state"]
        t0 = time.perf_counter()
        for _ in range(reps):
            s, mm = chunk(s, chunk_batch)
        jax.block_until_ready(mm["qloss_sum"])
        holder["state"] = s
        return reps * graphs_per_chunk / (time.perf_counter() - t0)

    return run_window


def make_ceiling(ds, cfg):
    """Two cached-chunk replay ceilings decomposing the fit() gap:

    - **packed** — one device-resident PACKED scan chunk re-fed to the
      jitted train program: pure model compute + dispatch, the absolute
      ceiling.
    - **compact** — one device-resident COMPACT-recipe chunk re-fed to the
      production compact train program (device-side expansion +
      materialization from the chip-resident arenas, exactly what fit()
      runs): fit/compact isolates the INPUT PIPELINE cost (host packing +
      recipe transfer), while compact/packed isolates the on-device
      expansion cost.

    Returns (run_packed, run_compact, flops/graph, bytes/graph)."""
    import itertools

    import jax
    import jax.numpy as jnp
    import optax

    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import (_chunk_iter, _host_chunks,
                                        create_train_state,
                                        make_train_chunk,
                                        make_train_chunk_compact)
    from pertgnn_tpu.batching.arena import zero_masked_compact
    from pertgnn_tpu.utils.flops import compiled_cost

    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = optax.adam(cfg.train.lr)
    host = list(itertools.islice(ds.batches("train"), cfg.train.scan_chunk))
    graphs_per_chunk = sum(int(b.graph_mask.sum()) for b in host)
    chunk_batch = next(_chunk_iter(iter(host), cfg.train.scan_chunk))
    b0 = jax.tree.map(lambda a: jnp.asarray(a[0]), chunk_batch)
    state = create_train_state(model, tx, b0, cfg.train.seed)
    chunk = make_train_chunk(model, cfg, tx)

    flops_per_graph = bytes_per_graph = None
    fl, by = compiled_cost(chunk, state, chunk_batch)
    if fl is not None:
        flops_per_graph = fl / graphs_per_chunk
    if by is not None:
        bytes_per_graph = by / graphs_per_chunk

    run_packed = _window_runner(chunk, state, chunk_batch, graphs_per_chunk)

    # compact twin: same leading batches as O(graphs) recipes, resident
    chost = list(itertools.islice(ds.compact_batches("train"),
                                  cfg.train.scan_chunk))
    cgraphs = sum(int(c.graph_mask.sum()) for c in chost)
    cchunk_batch = jax.tree.map(
        jnp.asarray,
        next(_host_chunks(iter(chost), cfg.train.scan_chunk,
                          zero_masked_compact)))
    # shared with fit(): one HBM-resident arena copy for the whole bench
    dev = ds.device_arenas()
    cstate = create_train_state(model, tx, b0, cfg.train.seed)
    cchunk = make_train_chunk_compact(model, cfg, tx, dev,
                                      ds.budget.max_nodes,
                                      ds.budget.max_edges)
    run_compact = _window_runner(cchunk, cstate, cchunk_batch, cgraphs)

    return run_packed, run_compact, flops_per_graph, bytes_per_graph


def bench_interleaved(ds, cfg, windows: int = 6):
    """fit() epochs interleaved with cached-chunk ceiling windows.

    Returns (fit_windows, packed_windows, compact_windows,
    flops_per_graph, bytes_per_graph): the per-epoch graphs/s of real
    training (epoch 0 dropped — compile) and both ceilings' window
    measurements taken BETWEEN those epochs (so tunnel/clock variance hits
    all three alike)."""
    from pertgnn_tpu.train.loop import fit

    from pertgnn_tpu.utils.flops import (peak_flops_per_chip,
                                         peak_hbm_bw_per_chip)

    run_packed, run_compact, flops_per_graph, bytes_per_graph = \
        make_ceiling(ds, cfg)
    # chip peaks are queried from the LIVE backend here; the finalizer
    # runs forced-CPU where they'd resolve to None, so they ride the
    # partial file alongside the flops/bytes they normalize
    _update_partial(phase="interleaved",
                    flops_per_graph=flops_per_graph,
                    bytes_per_graph=bytes_per_graph,
                    peak_flops_per_chip=peak_flops_per_chip(),
                    peak_hbm_bytes_per_s=peak_hbm_bw_per_chip())
    packed_windows: list[float] = []
    compact_windows: list[float] = []
    fit_rows: list[float] = []

    def hook(epoch: int, row: dict) -> None:
        # flush the fit window BEFORE the ceiling replays: those device
        # calls are as flap-prone as a fit epoch, and a wedge inside them
        # must not cost the fit measurement already in hand. Epoch/window
        # 0 is compile warm-up on every list — only the tails are usable.
        fit_rows.append(row["graphs_per_s"])
        _update_partial(fit_windows=fit_rows[1:])
        packed_windows.append(run_packed())
        compact_windows.append(run_compact())
        _update_partial(ceiling_windows=packed_windows[1:],
                        compact_windows=compact_windows[1:])

    _, history = fit(ds, cfg, epochs=windows + 1, profile_hook=hook)
    fit_windows = [row["graphs_per_s"] for row in history[1:]]
    return (fit_windows, packed_windows[1:], compact_windows[1:],
            flops_per_graph, bytes_per_graph)


def make_torch_reference(ds, cfg, f_in):
    """The reference's computation re-implemented in torch (CPU): model,
    one Adam train step, and a predict fn — used for the measured baseline
    (bench_torch_baseline) and the quality-parity benchmark
    (benchmarks/run.py). PyG TransformerConv semantics via scatter ops,
    BatchNorm1d, pinball loss — the reference stack's behavior on the same
    packed batches."""
    import torch

    hidden = cfg.model.hidden_channels

    class Conv(torch.nn.Module):
        def __init__(self, in_ch):
            super().__init__()
            self.q = torch.nn.Linear(in_ch, hidden)
            self.k = torch.nn.Linear(in_ch, hidden)
            self.v = torch.nn.Linear(in_ch, hidden)
            self.e = torch.nn.Linear(2 * hidden, hidden, bias=False)
            self.skip = torch.nn.Linear(in_ch, hidden)

        def forward(self, x, ee, snd, rcv):
            n = x.shape[0]
            q = self.q(x)[rcv]
            ke = self.k(x)[snd] + self.e(ee)
            ve = self.v(x)[snd] + self.e(ee)
            s = (q * ke).sum(-1) / np.sqrt(hidden)
            smax = torch.full((n,), -torch.inf).scatter_reduce(
                0, rcv, s, reduce="amax")
            # gathered only at rcv positions with edges -> always finite;
            # subtract the TRUE max (a 0-clamp would lose stabilization
            # for all-negative score groups and diverge from PyG)
            ex = torch.exp(s - smax[rcv])
            den = torch.zeros(n).index_add(0, rcv, ex)
            alpha = ex / den.clamp_min(1e-16)[rcv]
            out = torch.zeros(n, hidden).index_add(0, rcv,
                                                   ve * alpha[:, None])
            return out + self.skip(x)

    class MaskedBN(torch.nn.Module):
        """BatchNorm1d over REAL nodes only. The reference's ragged PyG
        batches contain no pad rows (pert_gnn.py:201-209), so a faithful
        re-implementation on packed batches must exclude padding from the
        batch statistics — torch.nn.BatchNorm1d would include it."""

        def __init__(self, ch, momentum=0.1, eps=1e-5):
            super().__init__()
            self.weight = torch.nn.Parameter(torch.ones(ch))
            self.bias = torch.nn.Parameter(torch.zeros(ch))
            self.register_buffer("running_mean", torch.zeros(ch))
            self.register_buffer("running_var", torch.ones(ch))
            self.momentum, self.eps = momentum, eps

        def forward(self, x, mask):
            # batch stats need >=2 real nodes (mean/var of an empty or
            # single-row selection would poison the running stats with
            # NaN/degenerate values); fall back to running stats below that
            if self.training and int(mask.sum()) >= 2:
                xm = x[mask]
                mean = xm.mean(0)
                var = xm.var(0, unbiased=False)
                with torch.no_grad():
                    n = xm.shape[0]
                    unbiased = var * n / max(n - 1, 1)
                    self.running_mean.mul_(1 - self.momentum).add_(
                        self.momentum * mean)
                    self.running_var.mul_(1 - self.momentum).add_(
                        self.momentum * unbiased)
            else:
                mean, var = self.running_mean, self.running_var
            y = (x - mean) * torch.rsqrt(var + self.eps)
            return y * self.weight + self.bias

    class Model(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.ms = torch.nn.Embedding(ds.num_ms, hidden)
            self.iface = torch.nn.Embedding(ds.num_interfaces, hidden)
            self.rpc = torch.nn.Embedding(ds.num_rpctypes, hidden)
            self.entry = torch.nn.Embedding(ds.num_entries, hidden)
            n_convs = max(2, cfg.model.num_layers)
            chans = [f_in + hidden] + [hidden] * (n_convs - 1)
            self.convs = torch.nn.ModuleList(Conv(c) for c in chans)
            self.bns = torch.nn.ModuleList(
                MaskedBN(hidden) for _ in range(n_convs - 1))
            self.g1 = torch.nn.Linear(2 * hidden, hidden)
            self.g2 = torch.nn.Linear(hidden, 1)

        def forward(self, b):
            x = torch.cat([b["x"], self.ms(b["ms_id"])], 1)
            # drop pad edges: the reference's ragged batches have none
            em = b["edge_mask"]
            snd, rcv = b["senders"][em], b["receivers"][em]
            ee = torch.cat([self.iface(b["edge_iface"][em]),
                            self.rpc(b["edge_rpctype"][em])], 1)
            nm = b["node_mask"]
            for i, conv in enumerate(self.convs[:-1]):
                x = torch.relu(self.bns[i](conv(x, ee, snd, rcv), nm))
            x = self.convs[-1](x, ee, snd, rcv)
            w = (b["pattern_prob"] / b["pattern_size"])[:, None]
            w = w * nm[:, None]
            g = b["node_graph"]
            pooled = torch.zeros(b["entry_id"].shape[0],
                                 hidden).index_add(0, g, x * w)
            gp = self.g2(torch.relu(self.g1(
                torch.cat([pooled, self.entry(b["entry_id"])], 1))))
            return gp[:, 0]

    def to_torch(b):
        d = {}
        for f in b._fields:
            a = np.asarray(getattr(b, f))
            if a.dtype == np.int32:
                d[f] = torch.tensor(a, dtype=torch.long)
            elif a.dtype == np.bool_:
                d[f] = torch.tensor(a)
            else:
                d[f] = torch.tensor(a, dtype=torch.float32)
        return d

    model = Model()
    opt = torch.optim.Adam(model.parameters(), lr=cfg.train.lr)
    tau = cfg.train.tau

    def one_step(b):
        model.train()
        opt.zero_grad()
        pred = model(b)
        e = b["y"] / cfg.train.label_scale - pred
        mask = b["graph_mask"].float()
        loss = (torch.maximum(tau * e, (tau - 1) * e)
                * mask).sum() / mask.sum().clamp_min(1.0)
        loss.backward()
        opt.step()
        return float(mask.sum())

    @torch.no_grad()
    def predict(b):
        model.eval()
        return (model(b) * cfg.train.label_scale).numpy()

    return model, one_step, predict, to_torch


def transfer_params_to_torch(tmodel, params, n_convs: int) -> None:
    """Copy one flax parameter set into the torch reference model — the
    two stacks then compute the same function (pinned to 2e-4 by
    tests/test_model.py weight-transfer parity). Shared by that test and
    the lockstep-trajectory study (benchmarks/span_gap_r4.py)."""
    import torch

    def put(t, a):
        with torch.no_grad():
            t.copy_(torch.tensor(np.asarray(a)))

    put(tmodel.ms.weight, params["ms_embed"]["embedding"])
    put(tmodel.iface.weight, params["interface_embed"]["embedding"])
    put(tmodel.rpc.weight, params["rpctype_embed"]["embedding"])
    put(tmodel.entry.weight, params["entry_embed"]["embedding"])
    for i in range(n_convs):
        cp, tc = params[f"conv_{i}"], tmodel.convs[i]
        for ours_name, theirs in (("query", tc.q), ("key", tc.k),
                                  ("value", tc.v), ("edge", tc.e),
                                  ("skip", tc.skip)):
            put(theirs.weight, np.asarray(cp[ours_name]["kernel"]).T)
            if ours_name != "edge":
                put(theirs.bias, cp[ours_name]["bias"])
    for i in range(n_convs - 1):
        put(tmodel.bns[i].weight, params[f"bn_{i}"]["scale"])
        put(tmodel.bns[i].bias, params[f"bn_{i}"]["bias"])
    put(tmodel.g1.weight, np.asarray(params["global_head1"]["kernel"]).T)
    put(tmodel.g1.bias, params["global_head1"]["bias"])
    put(tmodel.g2.weight, np.asarray(params["global_head2"]["kernel"]).T)
    put(tmodel.g2.bias, params["global_head2"]["bias"])


def bench_torch_baseline(ds, cfg, steps: int = 6) -> float:
    """The reference's computation in torch on CPU, same batches. The
    torch loop re-feeds pre-converted batches — this is the CEILING of the
    reference stack (its real loop re-collates on host every step,
    /root/reference/pert_gnn.py:219-231), so vs_baseline is conservative."""
    import itertools

    batches = list(itertools.islice(ds.batches("train"), 4))
    _, one_step, _, to_torch = make_torch_reference(
        ds, cfg, batches[0].x.shape[1])
    tbatches = [to_torch(b) for b in batches]
    one_step(tbatches[0])  # warm-up
    graphs = 0
    t0 = time.perf_counter()
    for i in range(steps):
        graphs += one_step(tbatches[i % len(tbatches)])
    dt = time.perf_counter() - t0
    return graphs / dt


def _probe_backend() -> bool:
    """Guard against a wedged TPU tunnel: if backend init hangs in a probe
    subprocess (observed with the axon relay: jax.devices() blocks
    forever), fall back to CPU so the bench still reports a number —
    clearly labeled via the `backend`/`backend_fallback` JSON fields —
    instead of hanging the driver.

    The relay wedges and un-wedges on minute timescales, so ONE long probe
    throws away later recovery windows: instead poll SEVERAL short probes
    (BENCH_PROBE_TRIES x BENCH_PROBE_TIMEOUT s, with a pause between) and
    take TPU if ANY succeeds. Total budget at the defaults (4 x 75 s +
    3 x 10 s pauses ~ 5.5 min) stays near the old single 240 s probe.
    Must run BEFORE the first jax import in this process. Returns True if
    the fallback engaged. Implementation is the shared polling probe in
    pertgnn_tpu.cli.common (also used by the driver's entry()).

    The verdict persists at benchmarks/backend_probe.json for the round
    (BENCH_r05 re-paid the full 4x75 s timeout budget before EVERY
    fallback run of the round); `--reprobe` forces a fresh probe."""
    import sys

    from pertgnn_tpu.cli.common import probe_backend_or_fallback
    return probe_backend_or_fallback(cache_path=_PROBE_CACHE,
                                     reprobe="--reprobe" in sys.argv[1:])


def _git_state() -> tuple[str | None, bool | None]:
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    try:
        commit = subprocess.run(
            ["git", "-C", here, "rev-parse", "HEAD"], capture_output=True,
            text=True, timeout=10).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        commit = None
    try:
        dirty = bool(subprocess.run(
            ["git", "-C", here, "status", "--porcelain"],
            capture_output=True, text=True, timeout=10).stdout.strip())
    except (OSError, subprocess.SubprocessError):
        dirty = None
    return commit, dirty


def _persist_last_good_tpu(result: dict, commit: str | None = None,
                           dirty: bool | None = None) -> None:
    """On a successful on-chip measurement, pin the JSON + commit hash to
    benchmarks/last_good_tpu.json so a mid-round tunnel-up window is never
    lost to the official record (VERDICT r3 weakness 1: the only r3 chip
    number was a stale manual run). `commit`/`dirty` override HEAD when
    finalizing a partial captured before later commits landed."""
    if commit is None:
        commit, dirty = _git_state()
    # atomic: the watcher gates future bench attempts on this file's
    # existence, so a timeout-kill mid-write must not leave a corrupt pin
    tmp = _PIN + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"commit": commit, "dirty_worktree": dirty,
                   "captured_unix_time": time.time(), **result}, f, indent=1)
    os.replace(tmp, _PIN)
    print(f"NOTE: on-chip result pinned to {_PIN} @ {commit}",
          file=__import__("sys").stderr)


def _assemble_result(*, fit_w, ceil_w, cceil_w, unstaged_w, flops_per_graph,
                     bytes_per_graph, baseline, backend, fallback,
                     train_graphs, partial_capture=False,
                     peak_flops=None, peak_bw=None, device_kind=None,
                     attention_impl="segment", serve_dtype="f32",
                     kernel_fallbacks=0):
    """Build the official result JSON from measured windows. Shared by the
    live path (main) and --finalize-partial (a wedge-killed capture with
    >=_MIN_FIT_WINDOWS usable fit windows); ceiling/A-B fields degrade to
    None when their windows were never reached. `peak_flops`/`peak_bw`
    override the live-backend query with the peaks recorded at capture
    time (the finalizer runs forced-CPU, where the query returns None);
    failing those, `device_kind` (also stamped at capture) resolves the
    peaks from the chip table so mfu_pct/mbu_pct stop degrading to null
    on salvaged chip captures — CPU runs stay honestly null (no peak is
    published for a host CPU)."""
    from pertgnn_tpu.utils.flops import (mbu, mfu, peak_flops_for_kind,
                                         peak_flops_per_chip,
                                         peak_hbm_bw_for_kind,
                                         peak_hbm_bw_per_chip,
                                         roofline_graphs_per_s)

    if peak_flops is None:
        peak_flops = (peak_flops_for_kind(device_kind) if device_kind
                      else peak_flops_per_chip())
    if peak_bw is None:
        peak_bw = (peak_hbm_bw_for_kind(device_kind) if device_kind
                   else peak_hbm_bw_per_chip())
    fit_med = statistics.median(fit_w)
    ceil_med = statistics.median(ceil_w) if ceil_w else None
    cceil_med = statistics.median(cceil_w) if cceil_w else None
    unstaged_med = statistics.median(unstaged_w) if unstaged_w else None
    eff = mfu(fit_med, flops_per_graph, peak=peak_flops)
    bw_eff = mbu(fit_med, bytes_per_graph, bw=peak_bw)
    roofline = roofline_graphs_per_s(flops_per_graph, bytes_per_graph,
                                     peak_f=peak_flops, peak_b=peak_bw)

    def spread_pct(ws):
        return round(100.0 * (max(ws) - min(ws)) / max(statistics.median(ws),
                                                       1e-9), 1)

    result = {
        "metric": "pert_e2e_fit_train_call_graphs_per_sec_per_chip",
        "value": round(fit_med, 1),
        "unit": "graphs/s",
        "vs_baseline": round(fit_med / baseline, 2),
        "fit_windows": [round(w, 1) for w in fit_w],
        "fit_spread_pct": spread_pct(fit_w),
        "ceiling_graphs_per_s": (round(ceil_med, 1)
                                 if ceil_med is not None else None),
        "ceiling_windows": [round(w, 1) for w in ceil_w],
        "ceiling_spread_pct": spread_pct(ceil_w) if ceil_w else None,
        "fit_over_ceiling": (round(fit_med / ceil_med, 3)
                             if ceil_med is not None else None),
        # the production compact program replayed on one resident chunk:
        # fit/compact = input-pipeline efficiency; compact/packed = cost
        # of on-device recipe expansion + arena materialization
        "compact_ceiling_graphs_per_s": (round(cceil_med, 1)
                                         if cceil_med is not None else None),
        "fit_over_compact_ceiling": (round(fit_med / cceil_med, 3)
                                     if cceil_med is not None else None),
        "compact_over_packed": (round(cceil_med / ceil_med, 3)
                                if ceil_med is not None
                                and cceil_med is not None else None),
        "fit_unstaged_graphs_per_s": (round(unstaged_med, 1)
                                      if unstaged_med is not None else None),
        "unstaged_windows": [round(w, 1) for w in unstaged_w],
        "staged_over_unstaged": (round(fit_med / unstaged_med, 3)
                                 if unstaged_med is not None else None),
        "mfu_pct": round(100 * eff, 2) if eff is not None else None,
        # MBU + roofline: the honest utilization story for a workload whose
        # arithmetic intensity sits far below the chip's roofline knee
        "mbu_pct": round(100 * bw_eff, 2) if bw_eff is not None else None,
        "roofline_graphs_per_s": (round(roofline, 1)
                                  if roofline is not None else None),
        "flops_per_graph": (round(flops_per_graph)
                            if flops_per_graph is not None else None),
        "bytes_per_graph": (round(bytes_per_graph)
                            if bytes_per_graph is not None else None),
        "peak_flops_per_chip": peak_flops,
        "peak_hbm_bytes_per_s": peak_bw,
        "device_kind": device_kind,
        # kernel-variant attribution (ISSUE 6): WHICH hot-path
        # implementation and serve tier produced these numbers, so
        # cross-round comparisons never mix variants silently. The
        # training dtype is f32; serve_dtype only matters for serve
        # captures but rides here for a uniform schema.
        "attention_impl": attention_impl,
        "serve_dtype": serve_dtype,
        # trace-time fallbacks observed during the measured programs: a
        # nonzero count means the numbers above (partly) ran the segment
        # path regardless of what attention_impl claims — --gate refuses
        # such a capture as a witness for its variant
        "kernel_fallbacks": int(kernel_fallbacks or 0),
        "baseline_torch_cpu_graphs_per_s": round(baseline, 1),
        "backend": backend,
        "backend_fallback": fallback,
        # what vs_baseline actually compares (VERDICT r4 #6): the torch
        # baseline always runs on this host's CPU, so the ratio is only a
        # cross-backend claim when our side ran on the chip
        "comparison": f"{backend}-vs-cpu",
        "train_graphs_per_epoch": train_graphs,
    }
    if partial_capture:
        result["partial_capture"] = True
        result["n_fit_windows"] = len(fit_w)
    return result


def _assemble_from_stitch(st: dict) -> dict:
    """The official result JSON from a journal stitch
    (telemetry/capture.stitch_windows): the SAME schema as a live
    single-window capture — every _assemble_result field, medians over
    the stitched union — plus the provenance the stitch contract
    stamps: `stitched: true`, per-window `windows_provenance`
    (window id, stage, wall time, capturing pid), the per-window
    roofline rows measured at capture time, and the entry/staleness
    accounting."""
    result = _assemble_result(
        fit_w=st["fit_w"], ceil_w=st["ceil_w"], cceil_w=st["cceil_w"],
        unstaged_w=[], flops_per_graph=st["flops_per_graph"],
        bytes_per_graph=st["bytes_per_graph"], baseline=st["baseline"],
        backend=st["backend"], fallback=st["fallback"],
        train_graphs=st["train_graphs"],
        partial_capture=not st["complete"],
        peak_flops=st["peak_flops"], peak_bw=st["peak_bw"],
        device_kind=st["device_kind"],
        attention_impl=st["attention_impl"],
        serve_dtype=st["serve_dtype"])
    result["stitched"] = True
    result["windows_provenance"] = st["provenance"]
    result["window_attribution"] = st["window_attribution"]
    result["stale_windows_dropped"] = st["stale_windows_dropped"]
    result["capture_entries"] = st["n_entries"]
    if st.get("wedged_stages"):
        result["wedged_stages"] = st["wedged_stages"]
    return result


def _journal_candidate() -> dict | None:
    """The capture journal as a finalize salvage candidate, shaped so
    _salvage_rank orders it against the partial/orphan files
    (`backend` + `fit_windows`) — this is how --finalize-partial folds
    into journal replay. Returns None when there is no journal or its
    fragments refuse to stitch (refusal reason printed, never
    silent)."""
    from pertgnn_tpu.telemetry import capture as cap

    if not os.path.exists(_JOURNAL):
        return None
    try:
        st = cap.stitch_windows(cap.CaptureJournal(_JOURNAL).records(),
                                min_fit_windows=_MIN_FIT_WINDOWS)
    except cap.StitchRefused as e:
        print(f"finalize-partial: capture journal not stitchable ({e})",
              flush=True)
        return None
    return {"backend": st["backend"], "fit_windows": st["fit_w"],
            "_stitch": st}


def finalize_partial() -> int:
    """Promote a wedge-killed capture's partial file into the official
    result. Host-only: forces the CPU backend (the relay factory is also
    removed by apply_platform_env) so a wedged tunnel can never hang the
    finalizer; the only compute is the torch-CPU baseline if the live run
    died before reaching it."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from pertgnn_tpu.cli.common import apply_platform_env
    apply_platform_env()

    # candidates: the latest attempt's partial, any orphaned salvage a
    # newer attempt displaced, and a stitchable capture journal — a TPU
    # capture outranks a CPU-fallback one regardless of window count
    # (only TPU results pin), then more windows
    p = max((_read_json(_PARTIAL), _read_json(_ORPHAN),
             _journal_candidate()),
            key=_salvage_rank)
    if not p:
        print("finalize-partial: no partial capture file", flush=True)
        return 1
    fit_w = p.get("fit_windows") or []
    if len(fit_w) < _MIN_FIT_WINDOWS:
        print(f"finalize-partial: only {len(fit_w)} fit windows "
              f"(< {_MIN_FIT_WINDOWS}); not promoting", flush=True)
        return 1
    # never downgrade: a full pin always wins; a partial pin survives
    # unless this candidate captured strictly more fit windows
    pin = _read_json(_PIN)
    if pin and pin.get("backend") == "tpu":
        if not pin.get("partial_capture"):
            print("finalize-partial: full pin already exists; keeping it",
                  flush=True)
            _discard_partials()
            return 0
        if _n_fit_windows(pin) >= len(fit_w):
            print(f"finalize-partial: existing partial pin has "
                  f"{_n_fit_windows(pin)} fit windows >= candidate's "
                  f"{len(fit_w)}; keeping it", flush=True)
            _discard_partials()
            return 0
    if "_stitch" in p:
        # journal replay: the stitch carries its own baseline (the
        # stitcher refuses fragments without one) and provenance
        st = p["_stitch"]
        result = _assemble_from_stitch(st)
        if result["backend"] == "tpu":
            _persist_last_good_tpu(result, commit=st.get("commit"),
                                   dirty=st.get("dirty"))
        _discard_partials()
        print(json.dumps(result))
        return 0
    baseline = p.get("baseline_torch_cpu_graphs_per_s")
    if baseline is None:
        ds, cfg = build_workload(p["traces_per_entry"])
        baseline = bench_torch_baseline(ds, cfg)
    result = _assemble_result(
        fit_w=fit_w, ceil_w=p.get("ceiling_windows") or [],
        cceil_w=p.get("compact_windows") or [],
        unstaged_w=p.get("unstaged_windows") or [],
        flops_per_graph=p.get("flops_per_graph"),
        bytes_per_graph=p.get("bytes_per_graph"),
        baseline=baseline, backend=p.get("backend", "unknown"),
        fallback=p.get("backend_fallback", False),
        train_graphs=p.get("train_graphs_per_epoch"),
        partial_capture=True,
        peak_flops=p.get("peak_flops_per_chip"),
        peak_bw=p.get("peak_hbm_bytes_per_s"),
        device_kind=p.get("device_kind"),
        attention_impl=p.get("attention_impl", "segment"),
        serve_dtype=p.get("serve_dtype", "f32"),
        kernel_fallbacks=p.get("kernel_fallbacks", 0))
    if result["backend"] == "tpu":
        _persist_last_good_tpu(result, commit=p.get("commit"),
                               dirty=p.get("dirty_worktree"))
    _discard_partials()
    print(json.dumps(result))
    return 0


def _history_records(root: str | None = None) -> list[dict]:
    """BENCH_r*.json round artifacts next to this file — the recorded
    throughput history `--gate` checks a finished run against. Rounds
    whose capture failed (rc != 0, no parsed record, no headline value)
    are skipped: they recorded an outage, not a throughput."""
    import glob

    root = root or os.path.dirname(os.path.abspath(__file__))
    out = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r[0-9]*.json"))):
        d = _read_json(path)
        if not d or d.get("rc") not in (0, None):
            continue
        parsed = d.get("parsed")
        if isinstance(parsed, dict) and parsed.get("value"):
            parsed = dict(parsed)
            parsed["_round"] = os.path.basename(path)
            out.append(parsed)
    return out


def gate_check(result: dict, history: list[dict]) -> tuple[bool, dict]:
    """Throughput-regression verdict for one finished run against the
    BENCH_r* history (pure function; tested in tests/test_bench_gate.py).

    Comparable = same backend AND same kernel variant — a blocked_dense
    capture is not a regression witness for a segment history row. The
    reference is the MOST RECENT comparable round (the trajectory's
    current state — older rounds ran on differently-loaded hosts; r03 vs
    r05 differ 33% on identical code, so gating on the historical max
    would flag host variance, not code). The floor is that round's
    headline minus ITS recorded fit-window spread: the noise band the
    capture itself measured. Below the floor = a real drop, not window
    jitter — exit nonzero, so this and every future perf PR is
    falsifiable. No comparable history passes vacuously (a first capture
    on a new backend/variant records a baseline, it cannot regress
    one)."""
    backend = result.get("backend")
    impl = result.get("attention_impl", "segment")
    metric = result.get("metric")
    comparable = [h for h in history
                  if h.get("backend") == backend
                  and h.get("attention_impl", "segment") == impl
                  # early rounds measured a DIFFERENT metric (r01/r02:
                  # per-call graphs/s, no backend field) — a row is only
                  # a witness for the same headline metric. Wildcard
                  # when either side predates the metric stamp.
                  and (metric is None or h.get("metric") is None
                       or h.get("metric") == metric)]
    detail = {"backend": backend, "attention_impl": impl,
              "comparable_rounds": [h["_round"] for h in comparable
                                    if "_round" in h]}
    nfall = int(result.get("kernel_fallbacks") or 0)
    if impl != "segment" and nfall:
        # the capture CLAIMS a kernel variant but its programs (partly)
        # traced the segment fallback — it is not a witness for this
        # variant's history, and passing it would launder segment numbers
        detail["kernel_fallbacks"] = nfall
        detail["verdict"] = (
            f"FAIL: {nfall} trace-time kernel fallback(s) — the capture "
            f"claims attention_impl={impl} but ran the segment path")
        return False, detail
    if not comparable:
        detail["verdict"] = "pass (no comparable history)"
        return True, detail
    ref = comparable[-1]  # rounds sort by filename = chronology
    spread_pct = float(ref.get("fit_spread_pct") or 0.0)
    value = float(result.get("value") or 0.0)
    # headline direction: latency metrics regress UPWARD — gate against
    # the reference plus its spread, not minus (a serve_bench p50 row in
    # the history must fail on a doubling, not on an improvement)
    lower_is_better = (result.get("unit") == "ms"
                       or str(metric or "").endswith("_ms")
                       or "latency" in str(metric or ""))
    if lower_is_better:
        bound = ref["value"] * (1.0 + spread_pct / 100.0)
        ok = value <= bound
        detail.update(
            reference_round=ref.get("_round"),
            reference_value=ref["value"],
            reference_spread_pct=spread_pct,
            ceiling_ms=round(bound, 3),
            value=value,
            verdict=("pass" if ok else
                     f"FAIL: {value} > ceiling {round(bound, 3)} "
                     f"(latest comparable {ref['value']} plus its "
                     f"{spread_pct}% window spread)"))
        return ok, detail
    floor = ref["value"] * (1.0 - spread_pct / 100.0)
    ok = value >= floor
    detail.update(
        reference_round=ref.get("_round"),
        reference_value=ref["value"],
        reference_spread_pct=spread_pct,
        floor_graphs_per_s=round(floor, 1),
        value=value,
        verdict=("pass" if ok else
                 f"FAIL: {value} < floor {round(floor, 1)} "
                 f"(latest comparable {ref['value']} minus its "
                 f"{spread_pct}% window spread)"))
    return ok, detail


def _analyzer_refusal(label: str, skip_env: str) -> list[str]:
    """New violations from one in-process stdlib analyzer (graftlint /
    graftsync), as strings — nonempty means --gate must refuse the
    capture: a tree that fails static analysis is not a valid perf
    witness, the same loud-refusal contract as the kernel-fallback
    check (a capture from a known-buggy tree would launder its numbers
    into the history). `skip_env`=1 is the explicit, greppable escape
    hatch, and a broken analyzer fails the gate LOUDLY, never passes
    it. The module is resolved via its `tools.<label>` package
    attribute at call time so tests can monkeypatch `run_repo`."""
    import importlib
    import sys

    if os.environ.get(skip_env, "") not in ("", "0"):
        print(f"WARNING: {skip_env} set — gating WITHOUT the "
              f"{label} check", file=sys.stderr)
        return []
    repo = os.path.dirname(os.path.abspath(__file__))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    try:
        mod = importlib.import_module(f"tools.{label}")
        result = mod.run_repo(repo)
    except Exception as e:
        print(f"WARNING: {label} could not run "
              f"({type(e).__name__}: {e}); refusing the gate",
              file=sys.stderr)
        return [f"{label} could not run: {type(e).__name__}: {e}"]
    return [str(v) for v in result.new]


def _graftlint_refusal() -> list[str]:
    """Source-level lint refusal (docs/LINTS.md)."""
    return _analyzer_refusal("graftlint", "BENCH_GATE_SKIP_LINT")


def _graftsync_refusal() -> list[str]:
    """Thread-protocol refusal: numbers captured from a tree whose
    lock order / Future custody / CV protocol / wait bounds fail
    verification are not a valid perf witness — tail latency measured
    over a racy dispatch path measures the race (docs/LINTS.md
    "graftsync")."""
    return _analyzer_refusal("graftsync", "BENCH_GATE_SKIP_SYNC")


def _graftaudit_refusal() -> list[str]:
    """New graftaudit violations over the stack's traced programs —
    nonempty means --gate must refuse the capture, exactly like the
    graftlint refusal: numbers captured from a tree whose compiled
    programs fail the IR audit (padding taint, silent f32 upcasts,
    lost donation, host callbacks) are not a valid perf witness.
    Runs in-process when this process already holds a multi-device CPU
    jax (the tier-1 path — the audit's toy programs are then built once
    per process and cached), and in a subprocess otherwise so the
    audit's CPU-backend tracing never contends with the bench process's
    own (possibly TPU) jax runtime.
    BENCH_GATE_SKIP_AUDIT=1 is the explicit, greppable escape hatch."""
    import subprocess
    import sys

    if os.environ.get("BENCH_GATE_SKIP_AUDIT", "") not in ("", "0"):
        print("WARNING: BENCH_GATE_SKIP_AUDIT set — gating WITHOUT the "
              "graftaudit check", file=sys.stderr)
        return []
    repo = os.path.dirname(os.path.abspath(__file__))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    if "jax" in sys.modules:
        import jax

        try:
            cpu_ready = (jax.default_backend() == "cpu"
                         and len(jax.devices()) >= 2)
        except RuntimeError:
            cpu_ready = False
        if cpu_ready:
            try:
                from tools.graftaudit import run_repo as audit_repo
                result = audit_repo()
            except Exception as e:
                print(f"WARNING: graftaudit could not run "
                      f"({type(e).__name__}: {e}); refusing the gate",
                      file=sys.stderr)
                return [f"graftaudit could not run: "
                        f"{type(e).__name__}: {e}"]
            return [str(v) for v in result.new]
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # the audit CLI forces CPU itself
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graftaudit", "--json"],
            cwd=repo, env=env, capture_output=True, text=True,
            timeout=600)
    except (OSError, subprocess.TimeoutExpired) as e:
        # a broken audit harness must fail the gate LOUDLY, not pass it
        print(f"WARNING: graftaudit could not run "
              f"({type(e).__name__}: {e}); refusing the gate",
              file=sys.stderr)
        return [f"graftaudit could not run: {type(e).__name__}: {e}"]
    if proc.returncode == 0:
        return []
    try:
        doc = json.loads(proc.stdout.strip().splitlines()[-1])
        return [f"{v['path']}: [{v['rule']}] {v['message']}"
                for v in doc.get("violations", [])] or [
                    f"graftaudit exited {proc.returncode} with no "
                    f"violation list"]
    except (json.JSONDecodeError, IndexError, KeyError, TypeError):
        tail = (proc.stderr or proc.stdout or "").strip()[-500:]
        return [f"graftaudit exited {proc.returncode}: {tail}"]


def gate_main(argv: list[str]) -> int:
    """`bench.py --gate [result.json]`: exit 1 when a finished run's
    headline throughput fell beyond the history's recorded window
    spread — or when the working tree fails `python -m tools.graftlint`,
    `python -m tools.graftsync`, or `python -m tools.graftaudit` (a
    capture from a tree that fails static analysis — source-level
    lint, thread-protocol verification, or traced-program audit — is
    refused outright, same pattern as the kernel-fallback refusal;
    BENCH_GATE_SKIP_LINT=1 / BENCH_GATE_SKIP_SYNC=1 /
    BENCH_GATE_SKIP_AUDIT=1 are the explicit hatches). The result
    record comes
    from the given path (a saved bench stdout line, or a BENCH_r-style
    wrapper whose `parsed` field holds it) or from stdin when piped."""
    import sys

    # usage validation FIRST: a mistyped invocation must exit 2 with
    # the one-line usage, not pay the ~3s lint and report a gate FAIL
    paths = [a for a in argv if not a.startswith("-")]
    usage = "--gate needs a result JSON path (or one piped on stdin)"
    if paths:
        with open(paths[0]) as f:
            result = json.load(f)
    elif not sys.stdin.isatty():
        raw = sys.stdin.read().strip()
        if not raw:
            print(usage, file=sys.stderr)
            return 2
        try:
            result = json.loads(raw)
        except json.JSONDecodeError as e:
            print(f"--gate: stdin is not a result JSON ({e})",
                  file=sys.stderr)
            return 2
    else:
        print(usage, file=sys.stderr)
        return 2
    if isinstance(result.get("parsed"), dict):
        result = result["parsed"]
    lint = _graftlint_refusal()
    if lint:
        print(json.dumps({"gate": {
            "verdict": (f"FAIL: graftlint reports {len(lint)} "
                        f"violation(s) in this working tree — a capture "
                        f"from a tree that fails static analysis is not "
                        f"a valid perf witness (fix or baseline them: "
                        f"python -m tools.graftlint)"),
            "graftlint": lint[:20],
        }}))
        return 1
    sync = _graftsync_refusal()
    if sync:
        print(json.dumps({"gate": {
            "verdict": (f"FAIL: graftsync reports {len(sync)} "
                        f"violation(s) in this working tree — a "
                        f"capture from a tree whose thread protocols "
                        f"fail static verification is not a valid "
                        f"perf witness (fix or justify them: python "
                        f"-m tools.graftsync)"),
            "graftsync": sync[:20],
        }}))
        return 1
    audit = _graftaudit_refusal()
    if audit:
        print(json.dumps({"gate": {
            "verdict": (f"FAIL: graftaudit reports {len(audit)} "
                        f"violation(s) over this tree's traced programs "
                        f"— a capture from a tree whose compiled "
                        f"programs fail the IR audit is not a valid "
                        f"perf witness (fix them: python -m "
                        f"tools.graftaudit)"),
            "graftaudit": audit[:20],
        }}))
        return 1
    ok, detail = gate_check(result, _history_records())
    print(json.dumps({"gate": detail}))
    return 0 if ok else 1


def precompile() -> int:
    """`bench.py --precompile`: populate the persistent compile cache
    with every program the bench's fit() + replay ceilings will run,
    then exit — no measurement. The watcher runs this the moment the
    tunnel answers (outside a capture window), so the window itself
    starts execute-only. Prints ONE JSON line of per-program compile
    seconds + cache hit/miss counts (cache-hit-dominated output means a
    previous stage already paid — the steady state)."""
    fallback = _probe_backend()
    from pertgnn_tpu.cli.common import apply_platform_env
    apply_platform_env()

    import jax

    from pertgnn_tpu.aot.precompile import precompile_train

    if not _CACHE_DIR:
        print("precompile: PERTGNN_COMPILE_CACHE_DIR is empty — nothing "
              "to populate", file=__import__("sys").stderr)
        return 1
    tpe = _TRACES_PER_ENTRY
    if ((fallback or jax.default_backend() == "cpu")
            and "BENCH_TRACES_PER_ENTRY" not in os.environ):
        tpe = _CPU_TRACES_PER_ENTRY
    ds, cfg = build_workload(tpe)
    # the ceilings replay the PACKED chunk program too — prime both
    stats = precompile_train(ds, cfg, include_packed=True)
    stats["metric"] = "precompile_cache_population"
    stats["backend_fallback"] = fallback
    stats["traces_per_entry"] = tpe
    print(json.dumps(stats))
    return 0


def capture_main(argv: list[str]) -> int:
    """`bench.py --capture`: the graftprobe journaled capture (ISSUE
    17). Decomposes the bench into the stage plan in
    telemetry/capture.py — probe, arena_warm, precompile, cost,
    baseline, then per-window fit/ceiling/compact steps — journals
    every completed stage, and re-enters at the first incomplete stage
    on the next invocation (a journaled stage NEVER re-runs). Exit
    codes: 0 = capture complete (stitched result JSON printed),
    3 = window closed with a stage in flight (re-enter to resume),
    4 = a stage wedged past the watchdog (diagnosis journaled +
    stack dumped; re-enter to resume).

    `--simulate-windows` shrinks the workload (BENCH_TRACES_PER_ENTRY
    default 48, BENCH_WINDOWS default 2) for the CI resume drill;
    `--budget-stages N` closes the window after N completed stages
    (the deterministic mid-stage kill); BENCH_CAPTURE_BUDGET_S bounds
    an entry by wall seconds the same way. Per-window numbers are
    conservative: each entry's first fit window carries that process's
    in-process warm-up (trace + compile-cache replay), exactly what a
    real sub-minute window pays.

    A journal whose last entry ran a different commit, config
    fingerprint, or backend is rotated to `.superseded` — fragments
    from different trees or chips must never stitch."""
    import sys

    from pertgnn_tpu.telemetry import capture as cap

    simulate = "--simulate-windows" in argv
    budget_stages = None
    if "--budget-stages" in argv:
        budget_stages = int(argv[argv.index("--budget-stages") + 1])
    budget_s = float(os.environ.get("BENCH_CAPTURE_BUDGET_S", "0")) or None
    watchdog_s = float(os.environ.get("BENCH_CAPTURE_WATCHDOG_S", "600"))

    fallback = _probe_backend()
    from pertgnn_tpu.cli.common import apply_platform_env
    apply_platform_env()

    import jax

    from pertgnn_tpu.aot import enable_compile_cache
    from pertgnn_tpu.config import CompileCacheConfig
    from pertgnn_tpu.telemetry import watch_xla_cache
    from pertgnn_tpu.telemetry.devmem import sample_device_memory

    enable_compile_cache(CompileCacheConfig(cache_dir=_CACHE_DIR))
    cache_watch = watch_xla_cache()
    cache_counts = cache_watch.__enter__()

    backend = jax.default_backend()
    device_kind = getattr(jax.devices()[0], "device_kind", "") or ""
    if simulate:
        windows = int(os.environ.get("BENCH_WINDOWS", "2"))
        tpe = int(os.environ.get("BENCH_TRACES_PER_ENTRY", "48"))
    else:
        windows = _WINDOWS
        tpe = _TRACES_PER_ENTRY
        if ((fallback or backend == "cpu")
                and "BENCH_TRACES_PER_ENTRY" not in os.environ):
            tpe = _CPU_TRACES_PER_ENTRY
    commit, dirty = _git_state()
    config_fp = {"traces_per_entry": tpe, "windows": windows,
                 "attention_impl": os.environ.get("BENCH_ATTENTION_IMPL",
                                                  "segment"),
                 "simulate": simulate}
    journal = cap.CaptureJournal(_JOURNAL)
    prior_fp = cap.run_fingerprint(journal.records())
    live_fp = (commit, json.dumps(config_fp, sort_keys=True), backend)
    if prior_fp is not None and prior_fp != live_fp:
        superseded = _JOURNAL + ".superseded"
        os.replace(_JOURNAL, superseded)
        print(f"NOTE: capture identity changed ({prior_fp} -> {live_fp});"
              f" journal rotated to {superseded}", file=sys.stderr)
    journal.append(cap.RUN_EVENT, {
        "commit": commit, "dirty_worktree": dirty, "config": config_fp,
        "backend": backend, "device_kind": device_kind,
        "backend_fallback": fallback, "simulate": simulate})
    prior = cap.completed_stages(journal.records())

    # lazy per-entry state: an entry that only needs (say) two fit
    # windows must not pay make_ceiling; an entry that resumes past
    # arena_warm still rebuilds the workload (warm: mmap'd arena store)
    # but reads the journaled COST fields instead of re-deriving them
    state: dict = {}

    def _workload():
        if "ds" not in state:
            state["ds"], state["cfg"] = build_workload(tpe)
            from pertgnn_tpu.config import resolve_attention_impl
            state["impl"] = resolve_attention_impl(state["cfg"].model)
        return state["ds"], state["cfg"]

    def _ceiling():
        if "run_packed" not in state:
            ds, cfg = _workload()
            from pertgnn_tpu.utils.flops import (peak_flops_per_chip,
                                                 peak_hbm_bw_per_chip)
            (state["run_packed"], state["run_compact"], fl, by) = \
                make_ceiling(ds, cfg)
            state["cost"] = {
                "flops_per_graph": fl, "bytes_per_graph": by,
                "peak_flops_per_chip": peak_flops_per_chip(),
                "peak_hbm_bytes_per_s": peak_hbm_bw_per_chip(),
                "device_kind": device_kind, "backend": backend}
        return state["run_packed"], state["run_compact"], state["cost"]

    def _cost_fields() -> dict:
        return state.get("cost") or prior.get("cost") or {}

    def _attribution(graphs_per_s: float) -> dict:
        from pertgnn_tpu.utils.flops import variant_attribution
        cost = _cost_fields()
        return variant_attribution(
            attention_impl=state.get("impl", config_fp["attention_impl"]),
            dtype="f32", graphs_per_s=graphs_per_s,
            flops_per_graph=cost.get("flops_per_graph"),
            bytes_per_graph=cost.get("bytes_per_graph"),
            peak_f=cost.get("peak_flops_per_chip"),
            peak_b=cost.get("peak_hbm_bytes_per_s"))

    def _profile_start(i: int) -> str | None:
        want = os.environ.get("BENCH_CAPTURE_PROFILE", "")
        on = want == "1" or (want == "" and backend == "tpu")
        if not on or i >= _PROFILE_MAX_WINDOWS:
            return None
        d = os.path.join(_PROFILE_DIR, f"window{i:02d}")
        try:
            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
            return d
        except Exception as e:
            print(f"WARNING: jax.profiler trace failed to start "
                  f"({type(e).__name__}: {e})", file=sys.stderr)
            return None

    def r_probe():
        return {"backend": backend, "device_kind": device_kind,
                "backend_fallback": fallback}

    def r_arena():
        ds, cfg = _workload()
        return {"train_graphs_per_epoch": len(ds.splits["train"]),
                "traces_per_entry": tpe, "backend": backend,
                "device_kind": device_kind,
                "attention_impl": state["impl"],
                "serve_dtype": cfg.serve.serve_dtype,
                "mem": sample_device_memory(where="capture_arena_warm")}

    def r_precompile():
        if not _CACHE_DIR:
            return {"skipped": "PERTGNN_COMPILE_CACHE_DIR empty"}
        ds, cfg = _workload()
        from pertgnn_tpu.aot.precompile import precompile_train
        stats = precompile_train(ds, cfg, include_packed=True)
        return {"total_seconds": round(stats["total_seconds"], 3),
                "programs": len(stats["programs"]),
                "xla_cache_hits": stats["xla_cache_hits"],
                "xla_cache_misses": stats["xla_cache_misses"],
                "mem": sample_device_memory(where="capture_precompile")}

    def r_cost():
        _, _, cost = _ceiling()
        return dict(cost)

    def r_baseline():
        ds, cfg = _workload()
        return {"baseline_torch_cpu_graphs_per_s":
                round(bench_torch_baseline(ds, cfg), 2)}

    def r_fit(i: int):
        ds, cfg = _workload()
        from pertgnn_tpu.train.loop import fit
        pdir = _profile_start(i)
        try:
            _, hist = fit(ds, cfg, epochs=1)
        finally:
            if pdir:
                try:
                    jax.profiler.stop_trace()
                except Exception as e:
                    print(f"WARNING: jax.profiler stop failed "
                          f"({type(e).__name__}: {e})", file=sys.stderr)
        row = hist[0]
        g = row["graphs_per_s"]
        return {"graphs_per_s": g, "backend": backend,
                "train_time_s": round(row["train_time_s"], 3),
                "ttfs_s": row.get("ttfs_s"),
                "roofline": _attribution(g),
                "mem": sample_device_memory(where="capture_window",
                                            window=i),
                "profile_dir": pdir}

    def r_ceil(i: int):
        run_packed, _, _ = _ceiling()
        g = run_packed()
        return {"graphs_per_s": g, "backend": backend,
                "roofline": _attribution(g)}

    def r_compact(i: int):
        _, run_compact, _ = _ceiling()
        return {"graphs_per_s": run_compact(), "backend": backend}

    plan = cap.stage_plan(windows)
    runners = {"probe": r_probe, "arena_warm": r_arena,
               "precompile": r_precompile, "cost": r_cost,
               "baseline": r_baseline}
    for i in range(windows):
        runners[f"window:{i:02d}:fit"] = lambda i=i: r_fit(i)
        runners[f"window:{i:02d}:ceiling"] = lambda i=i: r_ceil(i)
        runners[f"window:{i:02d}:compact"] = lambda i=i: r_compact(i)

    runner = cap.CaptureRunner(
        journal, plan, runners, budget_stages=budget_stages,
        budget_s=budget_s, watchdog_s=watchdog_s,
        dump_path=_JOURNAL + ".wedge.txt")
    try:
        outcome = runner.run()
    finally:
        cache_watch.__exit__(None, None, None)
    if outcome == cap.OUTCOME_WINDOW_CLOSED:
        nxt = cap.first_incomplete(plan, journal.records())
        print(f"capture: window closed with stage {nxt!r} in flight — "
              f"re-enter `bench.py --capture` to resume",
              file=sys.stderr)
        return cap.EXIT_WINDOW_CLOSED
    if outcome == cap.OUTCOME_WEDGED:
        print("capture: stage wedged past the watchdog (diagnosis "
              "journaled); re-enter `bench.py --capture` to resume",
              file=sys.stderr)
        return cap.EXIT_WEDGED
    st = cap.stitch_windows(
        journal.records(),
        min_fit_windows=max(1, min(_MIN_FIT_WINDOWS, windows)))
    result = _assemble_from_stitch(st)
    result["compile_cache"] = {
        "dir": _CACHE_DIR or None,
        "xla_cache_hits": cache_counts["hits"],
        "xla_cache_misses": cache_counts["misses"],
    }
    if result["backend"] == "tpu":
        _persist_last_good_tpu(result, commit=st["commit"],
                               dirty=st["dirty"])
    print(json.dumps(result))
    return 0


def main():
    fallback = _probe_backend()
    from pertgnn_tpu.cli.common import apply_platform_env
    apply_platform_env()  # honor JAX_PLATFORMS=cpu over the axon plugin

    import jax

    from pertgnn_tpu.aot import enable_compile_cache
    from pertgnn_tpu.config import CompileCacheConfig
    from pertgnn_tpu.telemetry import watch_xla_cache

    # compiles persist to (and replay from) disk for the whole run; the
    # watcher stays entered for the whole of main() — the hit/miss
    # split is the evidence of whether a precompile stage already paid
    # for this run's programs. The CM object must stay referenced: a
    # GC'd suspended generator runs its finally and would unregister
    # the listener mid-run.
    enable_compile_cache(CompileCacheConfig(cache_dir=_CACHE_DIR))
    cache_watch = watch_xla_cache()
    cache_counts = cache_watch.__enter__()

    # a promotable salvage from a previous attempt must survive until
    # something better exists: park it as the orphan (the finalizer falls
    # back to it if THIS attempt dies before _MIN_FIT_WINDOWS) — unless
    # the orphan slot already holds a higher-ranked salvage
    prev = _read_json(_PARTIAL)
    if (_n_fit_windows(prev) >= _MIN_FIT_WINDOWS
            and _salvage_rank(prev) > _salvage_rank(_read_json(_ORPHAN))):
        os.replace(_PARTIAL, _ORPHAN)
    else:
        try:
            os.remove(_PARTIAL)  # a present partial always = THIS attempt
        except OSError:
            pass
    tpe = _TRACES_PER_ENTRY
    if ((fallback or jax.default_backend() == "cpu")
            and "BENCH_TRACES_PER_ENTRY" not in os.environ):
        tpe = _CPU_TRACES_PER_ENTRY
    ds, cfg = build_workload(tpe)
    commit, dirty = _git_state()
    device_kind = getattr(jax.devices()[0], "device_kind", "") or ""
    from pertgnn_tpu.config import resolve_attention_impl
    impl = resolve_attention_impl(cfg.model)
    _update_partial(phase="workload_built", commit=commit,
                    dirty_worktree=dirty, traces_per_entry=tpe,
                    backend=jax.default_backend(),
                    device_kind=device_kind,
                    backend_fallback=fallback,
                    attention_impl=impl,
                    serve_dtype=cfg.serve.serve_dtype,
                    train_graphs_per_epoch=len(ds.splits["train"]))
    fit_w, ceil_w, cceil_w, flops_per_graph, bytes_per_graph = \
        bench_interleaved(ds, cfg, windows=_WINDOWS)
    # torch-CPU baseline BEFORE the flap-prone A/B: it cannot wedge, and
    # once it lands the partial file holds a complete promotable headline
    baseline = bench_torch_baseline(ds, cfg)
    _update_partial(phase="baseline_done",
                    baseline_torch_cpu_graphs_per_s=baseline)
    # Direct A/B of the round-4 flagship change in the SAME capture
    # window: the identical fit() with per-chunk recipe transfers
    # (stage_epoch_recipes=False) — on the tunnel each small device_put
    # costs ~3.5 ms x 4 fields x chunks/epoch, the mechanism behind the
    # r3 on-chip fit_over_ceiling of 0.659 (bench_r3_tpu.json predates
    # staging, so without this row the staged lever would only ever be
    # inferred across rounds, never measured in one window).
    import dataclasses as _dc

    from pertgnn_tpu.train.loop import fit as _fit
    cfg_uns = cfg.replace(train=_dc.replace(cfg.train,
                                            stage_epoch_recipes=False))
    # Guarded: a tunnel flap during this EXTRA measurement must not
    # discard the already-captured main windows. (A flap that BLOCKS
    # instead of raising is covered by the partial file + finalizer.)
    try:
        _, hist_u = _fit(ds, cfg_uns, epochs=max(3, _WINDOWS // 2) + 1)
        unstaged_w = [r["graphs_per_s"] for r in hist_u[1:]]
        _update_partial(phase="ab_done", unstaged_windows=unstaged_w)
    except Exception as e:
        print(f"WARNING: unstaged A/B fit failed ({type(e).__name__}: "
              f"{e}); emitting nulls for the A/B fields")
        unstaged_w = []
    from pertgnn_tpu.models import layers as _layers
    nfall = sum(_layers.FALLBACK_COUNTS.values())
    _update_partial(kernel_fallbacks=nfall)
    result = _assemble_result(
        fit_w=fit_w, ceil_w=ceil_w, cceil_w=cceil_w, unstaged_w=unstaged_w,
        flops_per_graph=flops_per_graph, bytes_per_graph=bytes_per_graph,
        baseline=baseline, backend=jax.default_backend(), fallback=fallback,
        train_graphs=len(ds.splits["train"]), device_kind=device_kind,
        attention_impl=impl, serve_dtype=cfg.serve.serve_dtype,
        kernel_fallbacks=nfall)
    result["compile_cache"] = {
        "dir": _CACHE_DIR or None,
        "xla_cache_hits": cache_counts["hits"],
        "xla_cache_misses": cache_counts["misses"],
    }
    if result["backend"] == "tpu":
        _persist_last_good_tpu(result, commit=commit, dirty=dirty)
    else:
        # CPU fallback at capture time: if the watcher pinned an on-chip
        # result earlier in the round, carry it inside this JSON so the
        # round artifact holds the chip evidence next to the fallback
        # number instead of forcing readers to a second file
        pin = _read_json(_PIN)
        if pin and pin.get("backend") == "tpu":
            result["last_good_tpu"] = {
                k: pin.get(k) for k in (
                    "commit", "captured_unix_time", "value", "unit",
                    "vs_baseline", "fit_over_ceiling",
                    "ceiling_graphs_per_s", "staged_over_unstaged",
                    "partial_capture", "n_fit_windows")
                if k in pin}
    # complete capture: the official JSON wins — but a CPU fallback must
    # not destroy an unfinalized TPU salvage it didn't supersede
    _discard_partials(keep_tpu_salvage=(result["backend"] != "tpu"))
    print(json.dumps(result))


if __name__ == "__main__":
    import sys

    if "--capture" in sys.argv[1:]:
        raise SystemExit(capture_main(sys.argv[1:]))
    if "--finalize-partial" in sys.argv[1:]:
        raise SystemExit(finalize_partial())
    if "--precompile" in sys.argv[1:]:
        raise SystemExit(precompile())
    if "--gate" in sys.argv[1:]:
        raise SystemExit(
            gate_main([a for a in sys.argv[1:] if a != "--gate"]))
    main()
