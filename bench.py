"""Benchmark: call-graphs/sec/chip on the flagship training step.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "graphs/s", "vs_baseline": N}

The baseline is MEASURED here, not looked up (the reference publishes no
numbers — BASELINE.md): a faithful torch-CPU re-implementation of the
reference's training step (PyG TransformerConv semantics via torch scatter
ops, BatchNorm1d, Adam, pinball loss) runs on the same packed batches on this
host — i.e. what the reference stack would do on the available non-TPU
hardware. vs_baseline = our graphs/s divided by torch's graphs/s.

Configuration mirrors the reference defaults (hidden 32, batch 170,
pert graphs; pert_gnn.py:15-33) on a synthetic workload sized to keep the
bench under a few minutes.
"""

from __future__ import annotations

import json
import time

import numpy as np


def build_workload():
    import jax

    from pertgnn_tpu.batching import build_dataset
    from pertgnn_tpu.config import Config, DataConfig, IngestConfig, ModelConfig, TrainConfig
    from pertgnn_tpu.ingest import synthetic
    from pertgnn_tpu.ingest.preprocess import preprocess

    cfg = Config(
        ingest=IngestConfig(min_traces_per_entry=5),
        data=DataConfig(max_traces=100_000, batch_size=170),
        # the fused kernel runs compiled only on TPU; off-TPU it would
        # fall to (very slow) interpret mode
        model=ModelConfig(hidden_channels=32, num_layers=3,
                          use_pallas_attention=(
                              jax.default_backend() == "tpu")),
        train=TrainConfig(lr=3e-4, label_scale=1000.0, scan_chunk=8),
        graph_type="pert",
    )
    data = synthetic.generate(synthetic.SyntheticSpec(
        num_microservices=60, num_entries=8, patterns_per_entry=4,
        traces_per_entry=400, seed=42))
    pre = preprocess(data.spans, data.resources, cfg.ingest)
    ds = build_dataset(pre, cfg)
    return ds, cfg


def bench_jax(ds, cfg, steps: int = 200) -> float:
    import jax
    import jax.numpy as jnp
    import optax

    from pertgnn_tpu.models.pert_model import make_model
    from pertgnn_tpu.train.loop import (create_train_state, make_train_chunk,
                                        _chunk_iter)

    model = make_model(cfg.model, ds.num_ms, ds.num_entries,
                       ds.num_interfaces, ds.num_rpctypes)
    tx = optax.adam(cfg.train.lr)
    host_batches = list(ds.batches("train"))[:cfg.train.scan_chunk]
    graphs_per_chunk = sum(int(b.graph_mask.sum()) for b in host_batches)
    chunk_batch = next(_chunk_iter(iter(host_batches), cfg.train.scan_chunk))
    b0 = jax.tree.map(lambda a: jnp.asarray(a[0]), chunk_batch)
    state = create_train_state(model, tx, b0, cfg.train.seed)
    chunk = make_train_chunk(model, cfg, tx)

    state, m = chunk(state, chunk_batch)  # compile
    jax.block_until_ready(m["qloss_sum"])

    n_chunks = max(1, steps // cfg.train.scan_chunk)
    t0 = time.perf_counter()
    for _ in range(n_chunks):
        state, m = chunk(state, chunk_batch)
    jax.block_until_ready(m["qloss_sum"])  # single sync at the end
    dt = time.perf_counter() - t0
    return n_chunks * graphs_per_chunk / dt


def make_torch_reference(ds, cfg, f_in):
    """The reference's computation re-implemented in torch (CPU): model,
    one Adam train step, and a predict fn — used for the measured baseline
    (bench_torch_baseline) and the quality-parity benchmark
    (benchmarks/run.py). PyG TransformerConv semantics via scatter ops,
    BatchNorm1d, pinball loss — the reference stack's behavior on the same
    packed batches."""
    import torch

    hidden = cfg.model.hidden_channels

    class Conv(torch.nn.Module):
        def __init__(self, in_ch):
            super().__init__()
            self.q = torch.nn.Linear(in_ch, hidden)
            self.k = torch.nn.Linear(in_ch, hidden)
            self.v = torch.nn.Linear(in_ch, hidden)
            self.e = torch.nn.Linear(2 * hidden, hidden, bias=False)
            self.skip = torch.nn.Linear(in_ch, hidden)

        def forward(self, x, ee, snd, rcv):
            n = x.shape[0]
            q = self.q(x)[rcv]
            ke = self.k(x)[snd] + self.e(ee)
            ve = self.v(x)[snd] + self.e(ee)
            s = (q * ke).sum(-1) / np.sqrt(hidden)
            smax = torch.full((n,), -torch.inf).scatter_reduce(
                0, rcv, s, reduce="amax")
            ex = torch.exp(s - smax.clamp_min(0.0)[rcv])
            den = torch.zeros(n).index_add(0, rcv, ex)
            alpha = ex / den.clamp_min(1e-16)[rcv]
            out = torch.zeros(n, hidden).index_add(0, rcv,
                                                   ve * alpha[:, None])
            return out + self.skip(x)

    class Model(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.ms = torch.nn.Embedding(ds.num_ms, hidden)
            self.iface = torch.nn.Embedding(ds.num_interfaces, hidden)
            self.rpc = torch.nn.Embedding(ds.num_rpctypes, hidden)
            self.entry = torch.nn.Embedding(ds.num_entries, hidden)
            n_convs = max(2, cfg.model.num_layers)
            chans = [f_in + hidden] + [hidden] * (n_convs - 1)
            self.convs = torch.nn.ModuleList(Conv(c) for c in chans)
            self.bns = torch.nn.ModuleList(
                torch.nn.BatchNorm1d(hidden) for _ in range(n_convs - 1))
            self.g1 = torch.nn.Linear(2 * hidden, hidden)
            self.g2 = torch.nn.Linear(hidden, 1)

        def forward(self, b):
            x = torch.cat([b["x"], self.ms(b["ms_id"])], 1)
            ee = torch.cat([self.iface(b["edge_iface"]),
                            self.rpc(b["edge_rpctype"])], 1)
            for i, conv in enumerate(self.convs[:-1]):
                x = torch.relu(self.bns[i](
                    conv(x, ee, b["senders"], b["receivers"])))
            x = self.convs[-1](x, ee, b["senders"], b["receivers"])
            w = (b["pattern_prob"] / b["pattern_size"])[:, None]
            g = b["node_graph"]
            pooled = torch.zeros(b["entry_id"].shape[0],
                                 hidden).index_add(0, g, x * w)
            gp = self.g2(torch.relu(self.g1(
                torch.cat([pooled, self.entry(b["entry_id"])], 1))))
            return gp[:, 0]

    def to_torch(b):
        d = {}
        for f in b._fields:
            a = np.asarray(getattr(b, f))
            if a.dtype == np.int32:
                d[f] = torch.tensor(a, dtype=torch.long)
            elif a.dtype == np.bool_:
                d[f] = torch.tensor(a)
            else:
                d[f] = torch.tensor(a, dtype=torch.float32)
        return d

    model = Model()
    opt = torch.optim.Adam(model.parameters(), lr=cfg.train.lr)
    tau = cfg.train.tau

    def one_step(b):
        model.train()
        opt.zero_grad()
        pred = model(b)
        e = b["y"] / cfg.train.label_scale - pred
        mask = b["graph_mask"].float()
        loss = (torch.maximum(tau * e, (tau - 1) * e)
                * mask).sum() / mask.sum().clamp_min(1.0)
        loss.backward()
        opt.step()
        return float(mask.sum())

    @torch.no_grad()
    def predict(b):
        model.eval()
        return (model(b) * cfg.train.label_scale).numpy()

    return model, one_step, predict, to_torch


def bench_torch_baseline(ds, cfg, steps: int = 6) -> float:
    """The reference's computation in torch on CPU, same batches."""
    batches = list(ds.batches("train"))[:4]
    _, one_step, _, to_torch = make_torch_reference(
        ds, cfg, batches[0].x.shape[1])
    tbatches = [to_torch(b) for b in batches]
    one_step(tbatches[0])  # warm-up
    graphs = 0
    t0 = time.perf_counter()
    for i in range(steps):
        graphs += one_step(tbatches[i % len(tbatches)])
    dt = time.perf_counter() - t0
    return graphs / dt


def main():
    ds, cfg = build_workload()
    ours = bench_jax(ds, cfg)
    baseline = bench_torch_baseline(ds, cfg)
    print(json.dumps({
        "metric": "pert_train_call_graphs_per_sec_per_chip",
        "value": round(ours, 1),
        "unit": "graphs/s",
        "vs_baseline": round(ours / baseline, 2),
        "baseline_torch_cpu_graphs_per_s": round(baseline, 1),
    }))


if __name__ == "__main__":
    main()
