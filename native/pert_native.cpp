// Native data-path kernels for pertgnn_tpu (host-side hot loops).
//
// The reference's offline dataset build spends its time in per-trace Python
// loops (iterrows + per-row sorts in misc.py:221-302; README quotes 10+ hours
// for the full trace). This library implements the PERT stage-expansion and
// min-depth BFS over plain columnar arrays, called from Python via ctypes
// (pertgnn_tpu/native/bindings.py). Semantics mirror
// pertgnn_tpu/graphs/construct.py::build_pert_graph exactly (parity-tested).
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <queue>
#include <unordered_map>
#include <vector>

namespace {

struct Event {
  double time;
  int32_t order;  // emission order; stable tie-break like Python's sort
  bool is_end;
  int64_t dm;
  int64_t iface;
  int64_t rpctype;
};

}  // namespace

extern "C" {

// PERT activity-on-node expansion for ONE sanitized trace.
//
// Inputs: n sanitized span rows (um, dm, interface, rpctype, timestamp,
// endTimestamp) and the trace's root microservice id.
// Output buffers are caller-allocated with capacities:
//   senders/receivers:    4*n
//   edge_attr:            4*n * 4   (iface, rpctype, call_ind, same_ms_ind)
//   ms_id, node_depth:    4*n + 1
// Returns 0 on success; fills out_num_nodes / out_num_edges.
int pert_build(const int64_t* um, const int64_t* dm, const int64_t* iface,
               const int64_t* rpctype, const double* ts, const double* end_ts,
               int64_t n, int64_t root, int32_t* senders, int32_t* receivers,
               int32_t* edge_attr, int32_t* ms_id, float* node_depth,
               int64_t* out_num_nodes, int64_t* out_num_edges) {
  // --- caller order: count-descending, first-appearance tie-break
  //     (pandas value_counts semantics; construct.py::_caller_order)
  std::vector<int64_t> first_order;
  std::unordered_map<int64_t, int64_t> counts;
  first_order.reserve(n);
  for (int64_t i = 0; i < n; ++i) {
    auto it = counts.find(um[i]);
    if (it == counts.end()) {
      counts.emplace(um[i], 1);
      first_order.push_back(um[i]);
    } else {
      ++it->second;
    }
  }
  std::vector<int64_t> order(first_order.size());
  for (size_t i = 0; i < order.size(); ++i) order[i] = (int64_t)i;
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return counts[first_order[a]] > counts[first_order[b]];
  });

  // --- stage nodes: caller with k calls -> chain of 2k+1 nodes
  //     (misc.py:240-250 semantics)
  std::unordered_map<int64_t, std::pair<int64_t, int64_t>> stages;  // ms -> [first, count]
  int64_t num_nodes = 0;
  int64_t num_edges = 0;
  auto push_edge = [&](int64_t s, int64_t r, int64_t a0, int64_t a1,
                       int64_t a2, int64_t a3) {
    senders[num_edges] = (int32_t)s;
    receivers[num_edges] = (int32_t)r;
    edge_attr[num_edges * 4 + 0] = (int32_t)a0;
    edge_attr[num_edges * 4 + 1] = (int32_t)a1;
    edge_attr[num_edges * 4 + 2] = (int32_t)a2;
    edge_attr[num_edges * 4 + 3] = (int32_t)a3;
    ++num_edges;
  };
  for (int64_t oi : order) {
    int64_t ms = first_order[oi];
    int64_t k = counts[ms];
    int64_t n_stages = 2 * k + 1;
    stages[ms] = {num_nodes, n_stages};
    for (int64_t s = 0; s + 1 < n_stages; ++s)
      push_edge(num_nodes + s, num_nodes + s + 1, 0, 0, 1, 1);
    for (int64_t s = 0; s < n_stages; ++s) ms_id[num_nodes + s] = (int32_t)ms;
    num_nodes += n_stages;
  }
  // leaf callees (in sorted order; construct.py uses sorted(set diff))
  std::vector<int64_t> leaves;
  for (int64_t i = 0; i < n; ++i)
    if (!counts.count(dm[i])) leaves.push_back(dm[i]);
  std::sort(leaves.begin(), leaves.end());
  leaves.erase(std::unique(leaves.begin(), leaves.end()), leaves.end());
  for (int64_t leaf : leaves) {
    stages[leaf] = {num_nodes, 1};
    ms_id[num_nodes] = (int32_t)leaf;
    ++num_nodes;
  }

  // --- per-caller call/return events sorted by time (misc.py:272-302);
  //     callers iterated in SORTED id order (pandas groupby), rows in
  //     original order, stable sort keeps equal-time emission order
  std::vector<int64_t> sorted_callers = first_order;
  std::sort(sorted_callers.begin(), sorted_callers.end());
  std::vector<Event> events;
  for (int64_t caller : sorted_callers) {
    events.clear();
    int32_t emit = 0;
    for (int64_t i = 0; i < n; ++i) {
      if (um[i] != caller) continue;
      events.push_back({ts[i], emit++, false, dm[i], iface[i], rpctype[i]});
      events.push_back({end_ts[i], emit++, true, dm[i], 0, 0});
    }
    std::stable_sort(events.begin(), events.end(),
                     [](const Event& a, const Event& b) {
                       return a.time < b.time;
                     });
    auto cs = stages[caller];
    for (size_t i = 0; i < events.size(); ++i) {
      const Event& ev = events[i];
      auto ds = stages[ev.dm];
      if (ev.is_end) {
        // return: last stage of callee -> caller stage i+1
        push_edge(ds.first + ds.second - 1, cs.first + (int64_t)i + 1,
                  ev.iface, ev.rpctype, 0, 0);
      } else {
        // call: caller stage i -> first stage of callee
        push_edge(cs.first + (int64_t)i, ds.first, ev.iface, ev.rpctype, 1,
                  0);
      }
    }
  }

  // --- min-depth BFS from the root's first stage; unreachable -> 0;
  //     normalized by max depth (construct.py::min_depth_from_root)
  std::vector<std::vector<int32_t>> adj(num_nodes);
  for (int64_t e = 0; e < num_edges; ++e)
    adj[senders[e]].push_back(receivers[e]);
  std::vector<int64_t> depth(num_nodes, -1);
  auto rs = stages.find(root);
  if (rs != stages.end()) {
    std::queue<int32_t> q;
    depth[rs->second.first] = 0;
    q.push((int32_t)rs->second.first);
    while (!q.empty()) {
      int32_t v = q.front();
      q.pop();
      for (int32_t w : adj[v])
        if (depth[w] < 0) {
          depth[w] = depth[v] + 1;
          q.push(w);
        }
    }
  }
  int64_t maxd = 0;
  for (int64_t i = 0; i < num_nodes; ++i)
    if (depth[i] > maxd) maxd = depth[i];
  double denom = maxd > 0 ? (double)maxd : 1.0;
  for (int64_t i = 0; i < num_nodes; ++i)
    node_depth[i] = depth[i] < 0 ? 0.0f : (float)((double)depth[i] / denom);

  *out_num_nodes = num_nodes;
  *out_num_edges = num_edges;
  return 0;
}

// Batched variant: rows for many traces concatenated, trace t owning rows
// [row_offsets[t], row_offsets[t+1]). Node/edge outputs are packed back to
// back; out_node_offsets/out_edge_offsets (length n_traces+1) locate them.
// Buffer capacities: edges 4*total_rows, nodes 4*total_rows + n_traces.
int pert_build_batch(const int64_t* um, const int64_t* dm,
                     const int64_t* iface, const int64_t* rpctype,
                     const double* ts, const double* end_ts,
                     const int64_t* row_offsets, const int64_t* roots,
                     int64_t n_traces, int32_t* senders, int32_t* receivers,
                     int32_t* edge_attr, int32_t* ms_id, float* node_depth,
                     int64_t* out_node_offsets, int64_t* out_edge_offsets) {
  int64_t node_base = 0, edge_base = 0;
  out_node_offsets[0] = 0;
  out_edge_offsets[0] = 0;
  for (int64_t t = 0; t < n_traces; ++t) {
    int64_t lo = row_offsets[t], hi = row_offsets[t + 1];
    int64_t nn = 0, ne = 0;
    int rc = pert_build(um + lo, dm + lo, iface + lo, rpctype + lo, ts + lo,
                        end_ts + lo, hi - lo, roots[t], senders + edge_base,
                        receivers + edge_base, edge_attr + edge_base * 4,
                        ms_id + node_base, node_depth + node_base, &nn, &ne);
    if (rc != 0) return rc;
    node_base += nn;
    edge_base += ne;
    out_node_offsets[t + 1] = node_base;
    out_edge_offsets[t + 1] = edge_base;
  }
  return 0;
}

}  // extern "C"
